"""Slot map + the batch remap seam + the admission/eviction barrier.

``vocab_mode = admit`` splits the id space from the table: the
pipeline parses/hashes ids into ``sketch.HASH_SPACE`` (the build-side
config swap in ``batch_iterator``/``StreamSource``), and every built
batch passes through ``remap`` — the ONE seam between hashed ids and
physical rows — before anything downstream sees it:

- an ADMITTED hashed id maps to its private physical row (slot map);
- every other id maps to the shared COLD row (row 0);
- the hash-space pad sentinel maps to the physical ``pad_id``;
- host-deduped batches are re-deduped after mapping (many cold ids
  collapse into one slot), so the "uniq_ids are unique, padding slots
  hold pad_id, the last slot is padding" invariants the jitted
  scatter relies on keep holding at EXACTLY the same array shapes.

The slot map is FROZEN between barriers (one atomic tuple the remap
reads), so the remap is deterministic, batch shapes never move, and
the device table is static between recompiles. ``barrier()`` — called
only at existing synchronization points (epoch boundary, publish
settle, final save) — decays the sketch, evicts rows whose decayed
frequency fell below ``vocab_admit_threshold`` (their table rows are
RESET to the cold-start state so a later owner never inherits stale
embeddings), admits the hottest waiting candidates into the freed +
free rows, and refreezes.

Observation is split from remapping so the sketch advances exactly
once per TRAINED example stream position: ``remap`` attaches the
batch's distinct hashed ids (``batch.vocab_obs``) and the train loop
calls ``note_trained`` only for batches it actually stepped — the
same adopt-on-step rule the stream watermark uses, which is what lets
the checkpointed admission state round-trip a preemption bit-exactly.
"""

from __future__ import annotations

import base64
import dataclasses
import functools
import heapq
import json
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from fast_tffm_tpu.vocab.sketch import HASH_SPACE, CountMinSketch

# The shared cold row: physical row 0 is RESERVED in admit mode —
# every unadmitted id gathers/trains through it, so the "millions of
# users" tail shares one embedding instead of aliasing random hot rows
# (what plain modulo collisions do). Admitted ids get rows
# [1, vocabulary_size).
COLD_ROW = 0

PAYLOAD_FORMAT = 1

# Candidate-buffer bound: ids that crossed the admission threshold but
# wait for the next barrier. 4x capacity comfortably covers any real
# churn between barriers; beyond it new candidates are dropped (and
# counted) rather than growing without bound on adversarial streams.
_CANDIDATE_CAP_FACTOR = 4

# Fixed row-reset program width: evicted-row resets pad to this many
# indices (pad slots point at the dead pad row) so the scatter
# compiles ONCE, never per eviction count — the zero-recompile
# guarantee covers barriers too.
RESET_CHUNK = 4096


def _b64(arr: np.ndarray) -> str:
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()
                            ).decode("ascii")


def _unb64(s: str, dtype) -> np.ndarray:
    return np.frombuffer(base64.b64decode(s), dtype).copy()


def _state_crc(state: Dict[str, object]) -> int:
    """crc32 of the canonical JSON serialization of ``state`` — the
    integrity check ``fmckpt verify`` re-runs on the sidecar."""
    blob = json.dumps(state, sort_keys=True).encode("utf-8")
    return zlib.crc32(blob) & 0xFFFFFFFF


def payload_crc_ok(payload: Dict[str, object]) -> bool:
    """Whether a vocab sidecar payload's embedded crc32 matches its
    state — shared by the restore path and fmckpt verify so the two
    can never disagree on what a torn sidecar is."""
    try:
        return int(payload["crc32"]) == _state_crc(payload["state"])
    except (KeyError, TypeError, ValueError):
        return False


def _tel():
    from fast_tffm_tpu.obs.telemetry import active
    return active()


class VocabMap:
    """Read-only remapper: the frozen (hashed id -> physical row)
    arrays plus the one batch transform. This is all inference needs —
    predict and the serving process load it from the checkpoint's
    vocab sidecar and never touch the sketch."""

    def __init__(self, capacity: int, pad_id: int,
                 keys: Optional[np.ndarray] = None,
                 rows: Optional[np.ndarray] = None):
        if capacity < 2:
            raise ValueError(
                f"vocab_mode = admit needs vocabulary_size >= 2 (one "
                f"cold row + at least one live row), got {capacity}")
        self.capacity = int(capacity)
        self.pad_id = int(pad_id)
        # One-tuple swap: remap (prefetch/build threads) reads this
        # reference once per call; barrier/load replace it atomically.
        self._frozen: Tuple[np.ndarray, np.ndarray] = (
            np.zeros(0, np.int64) if keys is None else keys,
            np.zeros(0, np.int32) if rows is None else rows)
        # Bumped on every slot-map movement (barrier refreeze, load):
        # remap stamps batches with it so ensure_current can catch a
        # batch that was remapped on the build side under a map a
        # barrier has since moved.
        self.generation = 0
        # False on eval_view() snapshots: a validation sweep's unique
        # tail must not skew the training stream's cold-hit rate.
        self.count_telemetry = True

    @staticmethod
    def build_cfg(cfg):
        """The config the BUILD side of the pipeline runs under in
        admit mode: identical except ids mod into HASH_SPACE (and the
        build-side pad sentinel becomes HASH_SPACE via ``pad_id``).
        ``remap`` converts everything back to the physical space."""
        return dataclasses.replace(cfg, vocabulary_size=HASH_SPACE)

    @classmethod
    def from_payload(cls, cfg, payload: Dict[str, object]) -> "VocabMap":
        """The inference-side load: checked against this config's
        capacity exactly like check_restored_vocab checks the table.
        Telemetry-silent, like eval_view: the vocab/* counters feed
        the TRAINING stream's cold-hit rate (the COLD-ROW SATURATION
        verdict), and a co-resident scorer's traffic — serve warmup
        batches are ~100% cold by construction — must not skew it."""
        state = _check_payload(cfg, payload)
        vm = cls(cfg.vocabulary_size, cfg.pad_id,
                 keys=_unb64(state["slot_keys"], np.int64),
                 rows=_unb64(state["slot_rows"], np.int32))
        vm.count_telemetry = False
        return vm

    @property
    def live_rows(self) -> int:
        return len(self._frozen[0])

    def _lookup_core(self, v64: np.ndarray):
        """(rows, hit) for hashed ids: admitted ids get their row +
        hit=True, everything else COLD_ROW + hit=False (the pad
        sentinel reads as a miss here — callers own pad handling)."""
        keys, rows = self._frozen
        if len(keys):
            idx = np.searchsorted(keys, v64)
            idx_c = np.minimum(idx, len(keys) - 1)
            hit = keys[idx_c] == v64
            out = np.where(hit, rows[idx_c],
                           np.int32(COLD_ROW)).astype(np.int32)
        else:
            out = np.full(v64.shape, COLD_ROW, np.int32)
            hit = np.zeros(v64.shape, bool)
        return out, hit

    def lookup(self, ids: np.ndarray) -> np.ndarray:
        """Hashed ids -> physical rows (COLD_ROW for unadmitted, the
        physical pad for the hash-space pad sentinel). Vectorized
        binary search over the frozen sorted keys; any shape."""
        v64 = np.asarray(ids).astype(np.int64, copy=False)
        out, _hit = self._lookup_core(v64)
        out[v64 == HASH_SPACE] = self.pad_id
        return out

    def remap(self, batch):
        """Hash-space DeviceBatch -> physical-space, IN PLACE (same
        object, same shapes), attaching ``batch.vocab_obs`` — the
        batch's distinct real hashed ids — for the train loop's
        adopt-on-step observation. Returns the batch.

        Host-dedup batches are re-deduplicated after mapping (every
        cold id collapses into one shared slot) WITHOUT a sort: the
        slot map is injective and the incoming real slots are already
        unique, so the mapped values split exactly into {distinct hit
        rows} + {cold} + {pad} — the new unique set is [cold?, hit
        rows..., pad fill], built by masks. The padding invariants
        hold by construction: pad fill slots hold pad_id, the last
        slot is padding (hits + the cold slot can never fill the
        array: the incoming batch always carries >= 1 pad slot, and
        the cold slot only exists when a miss freed one). This runs
        per batch on the hot path — the admission feature's whole
        overhead budget lives here.

        The hash-space originals are RETAINED on the batch
        (``vocab_src`` — references, not copies: the transform builds
        new arrays) together with the map generation, so
        ``ensure_current`` can redo the mapping if a barrier moves the
        slot map while the batch sits in a prefetch queue."""
        fresh = getattr(batch, "vocab_gen", None) is None
        # Generation captured BEFORE any _frozen read: a barrier
        # refreeze landing mid-remap then leaves the batch stamped
        # with the OLD generation, so ensure_current forces a (cheap,
        # harmless) redo instead of treating a stale mapping as
        # current.
        gen = self.generation
        if batch.uniq_ids is not None:
            batch.vocab_src = (batch.uniq_ids, batch.local_idx)
            u = batch.uniq_ids
            v64 = u.astype(np.int64)
            phys, hit = self._lookup_core(v64)
            real = v64 != HASH_SPACE
            hit &= real
            miss = real & ~hit
            n_hits = int(hit.sum())
            n_miss = int(miss.sum())
            base = 1 if n_miss else 0
            inv = np.empty(len(u), np.int32)
            inv[hit] = base + np.arange(n_hits, dtype=np.int32)
            if n_miss:
                inv[miss] = 0
            inv[~real] = base + n_hits  # first pad slot
            new_uniq = np.full(len(u), self.pad_id, np.int32)
            if n_miss:
                new_uniq[0] = COLD_ROW
            new_uniq[base:base + n_hits] = phys[hit]
            batch.uniq_ids = new_uniq
            batch.local_idx = inv[batch.local_idx]
            obs = v64[real]  # unique by the host-dedup contract
            n_cold = n_miss
        else:
            # Raw-ids batch (dedup = device / the serving path):
            # local_idx holds hashed ids directly; map cellwise — the
            # device unique pass then dedups physical rows. The
            # distinct-id extraction (an O(B*L log B*L) sort + a
            # second search pass) exists only for note_trained and the
            # cold-hit counters, so inference-side maps — the serving
            # flush is a latency-SLO hot path — skip it entirely.
            batch.vocab_src = (None, batch.local_idx)
            if self.count_telemetry:
                obs = np.unique(batch.local_idx).astype(np.int64)
                obs = obs[obs != HASH_SPACE]
                _rows, ohit = self._lookup_core(obs)
                n_cold = int(len(obs) - ohit.sum())
            else:
                obs, n_cold = None, 0
            batch.local_idx = self.lookup(batch.local_idx)
        batch.vocab_obs = obs
        batch.vocab_gen = gen
        # Count once per batch, on its FIRST remap (an ensure_current
        # redo must not double the cold-hit rate), and never from an
        # eval_view or an inference-side map (validation tails and
        # scoring traffic are not training traffic).
        if fresh and self.count_telemetry and obs is not None:
            tel = _tel()
            if tel is not None and len(obs):
                tel.count("vocab/ids", len(obs))
                tel.count("vocab/cold_ids", n_cold)
        return batch

    def ensure_current(self, batch):
        """Redo the remap iff the slot map moved since this batch was
        remapped (a barrier ran while it sat in a prefetch queue):
        without this, a stepped stale batch would scatter into rows
        the barrier evicted, reset, or reassigned to other ids. The
        common case — generations match — is one integer compare."""
        gen = getattr(batch, "vocab_gen", None)
        src = getattr(batch, "vocab_src", None)
        if gen == self.generation or src is None:
            return batch
        batch.uniq_ids, batch.local_idx = src
        return self.remap(batch)

    def eval_view(self) -> "VocabMap":
        """A telemetry-silent snapshot sharing the frozen arrays —
        validation sweeps remap through this so their held-out unique
        tail never inflates the cold-hit rate behind the COLD-ROW
        SATURATION verdict. Safe as a snapshot: barriers cannot run
        mid-sweep (single train thread)."""
        keys, rows = self._frozen
        vm = VocabMap(self.capacity, self.pad_id, keys=keys, rows=rows)
        vm.count_telemetry = False
        return vm


def _check_payload(cfg, payload: Dict[str, object]) -> Dict[str, object]:
    """Validate a vocab sidecar payload against this config; returns
    the inner state dict. Raises ValueError with the actionable
    mismatch — a slot map sized for a different table would silently
    scramble row ownership exactly like a vocab-size mismatch on the
    table itself (train.check_restored_vocab)."""
    if not payload_crc_ok(payload):
        raise ValueError(
            "vocab admission sidecar failed its crc32 check (torn or "
            "bit-rotted); inspect with `python -m tools.fmckpt verify`")
    state = payload["state"]
    if int(state["capacity"]) != cfg.vocabulary_size:
        raise ValueError(
            f"vocab admission state was written for vocabulary_size="
            f"{state['capacity']}, but this config has "
            f"{cfg.vocabulary_size}; restoring would misalign slot "
            "rows. Retrain, or fix the config.")
    if int(state["hash_space"]) != HASH_SPACE:
        raise ValueError(
            f"vocab admission state hashed ids into a {state['hash_space']}"
            f"-slot space; this build uses {HASH_SPACE}")
    return state


class VocabRuntime(VocabMap):
    """The training-side runtime: VocabMap + the sketch, the candidate
    buffer, and the barrier. Single-process by design (the slot map is
    host state; multi-worker admission needs a chief-decided broadcast
    — see ROADMAP item 3's sharded-table leg)."""

    def __init__(self, capacity: int, pad_id: int, threshold: float,
                 decay: float, sketch: CountMinSketch):
        super().__init__(capacity, pad_id)
        self.threshold = float(threshold)
        self.decay_factor = float(decay)
        self.sketch = sketch
        self._slots: Dict[int, int] = {}
        self._free: List[int] = list(range(1, capacity))  # heap: row 0
        # is the cold row, never assignable
        # Candidate buffer: O(1) per-batch array appends — the
        # barrier re-estimates the concatenation. ``_queued`` dedupes
        # across batches: an ever-present hot id must queue ONCE per
        # interval, not once per batch, or a handful of hot ids would
        # exhaust the cap and spuriously drop late-crossing ids.
        self._cand_chunks: List[np.ndarray] = []
        self._cand_len = 0
        self._queued: set = set()
        self._candidate_cap = _CANDIDATE_CAP_FACTOR * capacity
        # Stepped batches observed since the last REAL barrier: the
        # stream is the clock — a barrier with nothing trained behind
        # it is a no-op, so idle publish ticks and the back-to-back
        # epoch-boundary + final-save pair never double-decay the
        # sketch (which would evict still-hot ids on wall time alone).
        self._obs_batches = 0
        self.total_admitted = 0
        self.total_evicted = 0

    @classmethod
    def from_config(cls, cfg) -> "VocabRuntime":
        return cls(cfg.vocabulary_size, cfg.pad_id,
                   cfg.vocab_admit_threshold, cfg.vocab_decay,
                   CountMinSketch.from_mb(cfg.vocab_sketch_mb))

    # -- observation (train thread, adopt-on-step) ------------------------

    def note_trained(self, batch) -> None:
        """Feed the sketch with a STEPPED batch's distinct hashed ids
        (attached by remap) and queue the ones that crossed the
        admission threshold. Called only for trained batches — never
        validation/predict sweeps, never prefetched-but-unstepped
        batches — so the checkpointed sketch state corresponds exactly
        to the stream watermark beside it."""
        ids = getattr(batch, "vocab_obs", None)
        if ids is None or not len(ids):
            return
        self._obs_batches += 1
        est = self.sketch.observe_and_estimate(ids)
        hot_mask = est >= self.threshold
        if not hot_mask.any():
            return
        hot = ids[hot_mask]
        # Vectorized pre-filter: in steady state almost every hot id
        # is already admitted — only the cold remainder queues.
        _rows, admitted = self._lookup_core(
            hot.astype(np.int64, copy=False))
        hot = hot[~admitted]
        if not len(hot):
            return
        if self._queued:
            # Per-id set probes, but only over the unadmitted hot
            # remainder — steady state leaves this a handful of ids.
            mask = np.fromiter((int(i) not in self._queued
                                for i in hot), bool, len(hot))
            hot = hot[mask]
            if not len(hot):
                return
        room = self._candidate_cap - self._cand_len
        dropped = hot[room:] if room < len(hot) else hot[:0]
        hot = hot[:max(room, 0)]
        if len(dropped):
            tel = _tel()
            if tel is not None:
                tel.count("vocab/candidates_dropped", len(dropped))
            # Dropped ids join the membership set too — counted (and
            # dropped) ONCE per interval — but only while the set
            # itself stays bounded: on an adversarial stream whose
            # over-threshold ids far exceed the cap, an unbounded set
            # would be exactly the memory growth the cap rules out.
            # Beyond the bound, repeat drops may re-count; that only
            # over-states a counter that is already screaming.
            room_q = 2 * self._candidate_cap - len(self._queued)
            if room_q > 0:
                self._queued.update(dropped[:room_q].tolist())
        if not len(hot):
            return
        self._cand_chunks.append(hot.astype(np.int64, copy=False))
        self._cand_len += len(hot)
        self._queued.update(hot.tolist())

    # -- the barrier (epoch boundary / publish settle / final save) ------

    def barrier(self, reset_rows=None) -> Dict[str, int]:
        """Decay, evict, admit, refreeze — the ONE point the slot map
        moves. ``reset_rows(rows)`` is called with every freed
        physical row (sorted int32) so the table forgets the evicted
        owner's embedding: its id serves from the cold row afterwards,
        and a future owner of the row cold-starts instead of
        inheriting stale weights. Deterministic in the observation
        stream: eviction scans ids in sorted order, admission fills
        hottest-first with sorted-id tie-break.

        A barrier with NOTHING trained since the previous one is a
        no-op (the stream is the clock, like the watermark): idle
        publish ticks and the epoch-boundary/final-save pair must not
        stack decays and age out ids on wall time alone."""
        if self._obs_batches == 0:
            return {"admitted": 0, "evicted": 0,
                    "live": len(self._slots), "free": len(self._free)}
        self._obs_batches = 0
        self.sketch.decay(self.decay_factor)
        freed: List[int] = []
        if self._slots:
            keys = np.fromiter(self._slots.keys(), np.int64,
                               len(self._slots))
            keys.sort()
            est = self.sketch.estimate(keys)
            # Vectorized scan; the Python loop runs over EVICTED ids
            # only (churn-sized, not table-sized) — at 10^6 live rows
            # a per-slot interpreted pass would stall the train thread
            # for hundreds of ms at every publish barrier. The floor
            # is decay-scaled like admission's (both mean "pre-decay
            # estimate crossed threshold"): asymmetric floors would
            # leave a band of steady-rate ids oscillating
            # admit -> evict forever, wiping their embedding each
            # cycle.
            floor = self.threshold * self.decay_factor
            for k in keys[est < floor].tolist():
                freed.append(self._slots.pop(int(k)))
        for r in freed:
            heapq.heappush(self._free, r)
        evicted = len(freed)
        admitted = 0
        if self._cand_chunks and self._free:
            cand = np.unique(np.concatenate(self._cand_chunks))
            est = self.sketch.estimate(cand)
            # Re-check against the DECAY-SCALED floor: estimates here
            # already carry this barrier's own decay, and candidates
            # queued on the pre-decay basis (note_trained) — comparing
            # post-decay mass against the plain threshold would raise
            # the effective admission floor to threshold/decay, so an
            # id appearing at exactly the documented rate would never
            # admit. est >= threshold * decay IS "pre-decay est >=
            # threshold", which still drops candidates whose estimate
            # shrank for any other reason (a restore replay, float
            # drift) without double-charging the decay.
            keep = est >= self.threshold * self.decay_factor
            cand, est = cand[keep], est[keep]
            order = np.lexsort((cand, -est))  # hottest first, id tie
            for j in order.tolist():
                if not self._free:
                    break
                cid = int(cand[j])
                if cid in self._slots:
                    continue
                self._slots[cid] = heapq.heappop(self._free)
                admitted += 1
        self._cand_chunks.clear()
        self._cand_len = 0
        self._queued.clear()
        self._refreeze()
        if freed and reset_rows is not None:
            reset_rows(np.asarray(sorted(freed), np.int32))
        self.total_admitted += admitted
        self.total_evicted += evicted
        tel = _tel()
        if tel is not None:
            tel.count("vocab/admitted_rows", admitted)
            tel.count("vocab/evicted_rows", evicted)
            tel.set("vocab/live_rows", len(self._slots))
            tel.set("vocab/sketch_fill", self.sketch.fill_fraction())
        return {"admitted": admitted, "evicted": evicted,
                "live": len(self._slots), "free": len(self._free)}

    def _refreeze(self) -> None:
        if self._slots:
            # keys()/values() iterate in the same insertion order, so
            # one argsort aligns both — no per-key dict lookups at
            # table scale.
            keys = np.fromiter(self._slots.keys(), np.int64,
                               len(self._slots))
            rows = np.fromiter(self._slots.values(), np.int32,
                               len(self._slots))
            order = np.argsort(keys, kind="stable")
            keys = keys[order]
            rows = np.ascontiguousarray(rows[order])
        else:
            keys = np.zeros(0, np.int64)
            rows = np.zeros(0, np.int32)
        self._frozen = (keys, rows)  # single ref assignment: remap on
        # the prefetch thread sees the old pair or the new, never torn
        self.generation += 1

    # -- durability (the vocab-<step>.json.gz sidecar payload) ------------

    def state_payload(self) -> Dict[str, object]:
        """The crc-covered checkpoint sidecar payload. The slot map is
        serialized from the FROZEN arrays — what remap actually
        applied — so a restore reproduces the mapping bit-exactly even
        mid-interval (candidates re-accumulate from the replayed
        stream; they are derived state)."""
        keys, rows = self._frozen
        state = {
            "format": PAYLOAD_FORMAT,
            "hash_space": HASH_SPACE,
            "capacity": self.capacity,
            "threshold": self.threshold,
            "decay": self.decay_factor,
            "slot_keys": _b64(keys),
            "slot_rows": _b64(rows),
            "total_admitted": self.total_admitted,
            "total_evicted": self.total_evicted,
            "sketch": self.sketch.state(),
        }
        return {"format": PAYLOAD_FORMAT, "state": state,
                "crc32": _state_crc(state)}

    def load(self, cfg, payload: Dict[str, object]) -> None:
        """Restore the admission state a checkpoint carried: slot map,
        free list, sketch — bit-exact. Raises ValueError on crc or
        config mismatch (never silently trains against a scrambled
        map)."""
        state = _check_payload(cfg, payload)
        keys = _unb64(state["slot_keys"], np.int64)
        rows = _unb64(state["slot_rows"], np.int32)
        self._slots = {int(k): int(r) for k, r in zip(keys, rows)}
        used = set(self._slots.values())
        self._free = [r for r in range(1, self.capacity)
                      if r not in used]
        heapq.heapify(self._free)
        self._cand_chunks.clear()
        self._cand_len = 0
        self._queued.clear()
        self._obs_batches = 0
        self.total_admitted = int(state.get("total_admitted", 0))
        self.total_evicted = int(state.get("total_evicted", 0))
        self.sketch = CountMinSketch.from_state(state["sketch"])
        self._frozen = (keys, rows)
        self.generation += 1  # in-flight batches remapped pre-restore
        # must redo through ensure_current


# -- device-table row reset (the lookup.py seam's jitted form) -----------

def reset_body(table, acc, rows, adagrad_init: float):
    """The ONE cold-start definition every backend's jitted reset
    wrapper traces (device/mesh here, the pinned-offload placement in
    lookup._reset_rows_fn): zero embedding rows, re-init accumulator
    rows, RESET_CHUNK-wide index array. Changing what an evicted row's
    next owner inherits happens HERE, once."""
    import jax.numpy as jnp
    z = jnp.zeros((RESET_CHUNK, table.shape[1]), jnp.float32)
    a = jnp.full((RESET_CHUNK, acc.shape[1]), adagrad_init,
                 jnp.float32)
    return table.at[rows].set(z), acc.at[rows].set(a)


@functools.lru_cache(maxsize=None)
def _reset_fn(dim: int, adagrad_init: float):
    """ONE compiled scatter per (dim, adagrad_init): reset_body under
    plain jit. Index arrays are always RESET_CHUNK wide (pad slots
    point at the dead pad row, where a zero write is a no-op by the
    padding invariant), so eviction counts never change the compiled
    shape."""
    import jax

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def reset(table, acc, rows):
        return reset_body(table, acc, rows, adagrad_init)

    return reset


def reset_chunks(rows: np.ndarray, pad_row: int):
    """Yield RESET_CHUNK-wide int32 index chunks covering ``rows``,
    padded with ``pad_row`` (the dead row, where a reset write is a
    no-op by the padding invariant) — the ONE chunking contract every
    backend's eviction seam shares, so the fixed compiled shape can
    never drift between them."""
    rows = np.asarray(rows, np.int32)
    for a in range(0, len(rows), RESET_CHUNK):
        chunk = rows[a:a + RESET_CHUNK]
        if len(chunk) < RESET_CHUNK:
            chunk = np.concatenate(
                [chunk, np.full(RESET_CHUNK - len(chunk), pad_row,
                                np.int32)])
        yield chunk


def reset_table_rows(table, acc, rows: np.ndarray, pad_row: int,
                     adagrad_init: float):
    """Reset ``rows`` of a device-resident (or mesh-sharded) table +
    accumulator to the cold-start state, through the fixed-width
    compiled scatter. Returns the new (table, acc) pair."""
    fn = _reset_fn(int(table.shape[1]), float(adagrad_init))
    for chunk in reset_chunks(rows, pad_row):
        table, acc = fn(table, acc, chunk)
    return table, acc
