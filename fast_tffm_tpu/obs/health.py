"""Run-health watchdog: stall detection, stack forensics, crash events.

The metrics stream (obs/) answers "how fast"; this module answers the
operational questions aggregates can't: "why is this worker stuck"
(lockstep multi-worker waits are silent — a hung collective produces
no event at all), "when did loss go non-finite" (detected at the
existing barrier bulk-fetch in obs/sink.py — the scalars are already
host-side there, zero added device fetches), and "what was the run
doing before it crashed" (the drivers emit a ``crash`` event carrying
the traceback plus the sink's in-memory ring of recent events).

``Watchdog`` is a daemon thread fed by a heartbeat the train/predict
loops touch once per step (``RunTelemetry.heartbeat``). The beat is a
plain tuple assignment — atomic under the GIL, no lock on the hot
path. When no beat lands within ``stall_seconds`` the watchdog:

- emits a structured ``health`` event (``status = "stalled"``, last
  step, seconds since the last beat) and flushes the sink so the
  evidence reaches disk while the run is still wedged (a stalled run
  never reaches its next barrier);
- dumps ALL thread stacks via ``faulthandler`` into
  ``<metrics_file>.stacks`` — the "where is it stuck" answer:
  a parked ``queue.get``, a hung allgather, a wedged device transfer
  all show up by name.

One event per stall episode: the watchdog re-arms only after the beat
resumes (emitting ``status = "recovered"`` with the outage length so
the timeline shows the gap). Everything here is host-only — the
watchdog can never add a device fetch to the stream it guards.

Testability: the clock is injected and ``check()`` is callable
directly, so stall logic is pinned under a fake clock without real
sleeps; the thread loop is the same ``check()`` on a timer.
"""

from __future__ import annotations

import faulthandler
import threading
import time
from typing import Callable, Optional

# Floor on the poll interval: a tiny stall_seconds must not turn the
# watchdog into a busy loop.
MIN_POLL_SECONDS = 0.05


class Watchdog:
    """Daemon-thread stall detector over a run's telemetry sink.

    ``beat(step)`` is the hot-path surface (one tuple assignment);
    ``check()`` evaluates the stall state once (the thread calls it
    every ``stall_seconds / 4``); ``start()``/``stop()`` manage the
    thread. Pass ``clock`` to run the logic under a fake clock."""

    def __init__(self, sink, stall_seconds: float, stacks_path: str,
                 clock: Callable[[], float] = time.monotonic):
        self.sink = sink
        self.stall_seconds = float(stall_seconds)
        self.stacks_path = stacks_path
        self._clock = clock
        # Armed from construction: a run wedged in SETUP (checkpoint
        # restore against dead storage, a hung distributed bring-up)
        # stalls before its first step — exactly when forensics are
        # scarcest.
        self._beat = (self._clock(), -1)
        self._stalled_at: Optional[float] = None  # beat time the
        # current stall episode was declared against (None = healthy)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stall_events = 0

    # -- hot path --------------------------------------------------------
    def beat(self, step: Optional[int] = None) -> None:
        """Record progress. Tuple assignment: atomic under the GIL, so
        the hot loop never takes a lock for the watchdog."""
        if step is None:
            step = self._beat[1]
        self._beat = (self._clock(), step)

    # -- detection -------------------------------------------------------
    def check(self) -> Optional[str]:
        """One stall evaluation; returns the status it emitted ("stalled"
        / "recovered") or None. The thread calls this on a timer; tests
        call it directly under a fake clock."""
        beat_t, beat_step = self._beat
        now = self._clock()
        if self._stalled_at is None:
            if now - beat_t <= self.stall_seconds:
                return None
            # fmlint: disable=R008 -- single-writer by design: episode
            # state (_stalled_at, stall_events) is touched ONLY by
            # check(), which runs on the one watchdog thread (tests
            # call it directly with the thread stopped); the hot-path
            # beat() stays a GIL-atomic tuple assignment precisely so
            # the train loop never takes a lock for the watchdog
            self._stalled_at = beat_t
            self.stall_events += 1  # fmlint: disable=R008 -- same
            # single-writer episode state as _stalled_at above
            self.sink.emit("health", {
                "status": "stalled",
                "stalled_seconds": now - beat_t,
                "last_step": beat_step,
                "stacks_file": self.stacks_path,
            })
            self._dump_stacks(now - beat_t, beat_step)
            # Straight to disk: a stalled run won't reach a barrier.
            self.sink.flush()
            return "stalled"
        if beat_t > self._stalled_at:  # progress resumed
            outage = beat_t - self._stalled_at
            self._stalled_at = None  # fmlint: disable=R008 -- same
            # single-writer episode state: only check() clears it
            self.sink.emit("health", {
                "status": "recovered",
                "outage_seconds": outage,
                "last_step": beat_step,
            })
            self.sink.flush()
            return "recovered"
        return None

    def _dump_stacks(self, stalled_seconds: float, step: int) -> None:
        """All-thread stacks into the .stacks sidecar, appended with a
        header per episode. Never raises into the watchdog loop — a
        broken dump must not kill stall DETECTION."""
        try:
            with open(self.stacks_path, "a", encoding="utf-8") as fh:
                fh.write(f"\n==== stall after {stalled_seconds:.1f}s "
                         f"(last step {step}) at {time.time():.3f} "
                         f"====\n")
                fh.flush()
                faulthandler.dump_traceback(file=fh, all_threads=True)
        except Exception:  # fmlint: disable=R004 -- a broken stack
            # dump (unwritable sidecar) must not kill stall DETECTION;
            # the health event already reached the sink
            pass

    # -- thread lifecycle ------------------------------------------------
    def start(self) -> "Watchdog":
        if self._thread is None:
            interval = max(MIN_POLL_SECONDS, self.stall_seconds / 4.0)

            def loop():
                while not self._stop.wait(interval):
                    try:
                        self.check()
                    except Exception:  # fmlint: disable=R004 -- the
                        # watchdog daemon must outlive a bad check();
                        # dying here would silently disarm stall
                        # detection for the rest of the run
                        pass
            self._thread = threading.Thread(target=loop, name="watchdog",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None
        # Reaching an orderly stop() IS progress — the driver made it
        # to its close path — so beat once and evaluate a final time.
        # A stall still open from the last poll (fired during a long
        # final save, or recovered inside the final interval) closes
        # out as recovered instead of branding a finished run
        # 'NOT recovered'. A crashed run's verdict is owned by its
        # crash event (CRASHED outranks STALLED), and a hard-killed
        # run never reaches stop() — neither is masked by this.
        try:
            self.beat()
            self.check()
        except Exception:  # fmlint: disable=R004 -- best-effort final
            # recovered event on an already-stopping run; the sink may
            # legitimately be mid-close here
            pass


def emit_ckpt_fallback(step: int, reason: str, quarantined: str) -> None:
    """The state-plane fallback event (checkpoint.quarantine_step): a
    ``health: ckpt_fallback`` record + ``checkpoint/quarantined_steps``
    counter on the active run telemetry, flushed straight to disk — the
    very next thing the run does is retry an OLDER checkpoint, and if
    that also fails the evidence must already be on disk. No-op without
    an active run (offline tools like fmckpt verify without emitting)."""
    from fast_tffm_tpu.obs.telemetry import active
    tel = active()
    if tel is None:
        return
    tel.count("checkpoint/quarantined_steps")
    tel.sink.emit("health", {
        "status": "ckpt_fallback",
        "step": int(step),
        "reason": str(reason)[:300],
        "quarantined": quarantined,
    })
    tel.sink.flush()


def emit_worker_lost(lost, label: str,
                     timeout_seconds: Optional[float] = None,
                     error: Optional[str] = None) -> None:
    """The compute-plane diagnosis event (parallel/liveness.py): a
    ``health: worker_lost`` record naming exactly which peers stopped
    heartbeating (process id, host, lease age), flushed straight to
    disk — the survivors' very next move is to tear the distributed
    client down (elastic) or exit, so the evidence must already be
    durable. Counts ``cluster/workers_lost`` once per named peer.
    No-op without an active run (fake-clock unit tests install their
    own telemetry)."""
    from fast_tffm_tpu.obs.telemetry import active
    tel = active()
    if tel is None:
        return
    fields = {
        "status": "worker_lost",
        "label": str(label),
        "lost": [{"process_index": i.process_index, "host": i.host,
                  "pid": i.pid, "age_seconds": i.age_seconds}
                 for i in lost],
    }
    if timeout_seconds is not None:
        fields["timeout_seconds"] = float(timeout_seconds)
    if error is not None:
        fields["error"] = str(error)[:300]
    # The same dead peer is diagnosed from several angles (the lease
    # monitor's episode, the failed collective's conversion, the
    # deadline escalation) — the counter must say how many WORKERS
    # were lost, not how many paths noticed, so it counts each process
    # id once per run (the events themselves all land for forensics).
    seen = getattr(tel, "_workers_lost_counted", None)
    if seen is None:
        seen = tel._workers_lost_counted = set()
    fresh = {i.process_index for i in lost} - seen
    if not lost:
        fresh = {-1} - seen  # unnamed diagnosis: count once
    if fresh:
        seen.update(fresh)
        tel.count("cluster/workers_lost", len(fresh))
    tel.sink.emit("health", fields)
    tel.sink.flush()


def emit_elastic_recovery(generation: int, members, lost,
                          joined=(), capacity=None,
                          kind: str = "shrink") -> None:
    """The elastic recovery success event — both directions: survivors
    reformed into cluster generation ``generation`` with ``members``
    (original process indices) after losing ``lost`` (shrink), or
    after admitting ``joined`` replacement workers (grow).
    ``capacity`` is the original cluster size: fmstat renders
    ``RECOVERED (gen N, M workers)`` when the LAST recovery restores
    full membership, DEGRADED otherwise."""
    from fast_tffm_tpu.obs.telemetry import active
    tel = active()
    if tel is None:
        return
    tel.count("cluster/elastic_recoveries")
    if joined:
        tel.count("cluster/workers_joined", len(joined))
    fields = {
        "status": "elastic_recovered",
        "kind": str(kind),
        "generation": int(generation),
        "members": [int(m) for m in members],
        "lost": [int(p) for p in lost],
        "joined": [int(p) for p in joined],
    }
    if capacity is not None:
        fields["capacity"] = int(capacity)
    tel.sink.emit("health", fields)
    tel.sink.flush()


def format_crash(exc: BaseException, limit_chars: int = 8000) -> str:
    """The traceback text a crash event carries, tail-truncated (the
    frames nearest the raise are the forensic payload)."""
    import traceback
    text = "".join(traceback.format_exception(
        type(exc), exc, exc.__traceback__))
    return text[-limit_chars:]
