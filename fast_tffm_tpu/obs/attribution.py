"""Attribution analysis over a metrics JSONL stream.

The bench learned this lesson first: a single throughput number that
moves is undiagnosable until it's broken into host-only / device-only /
transfer-only ceilings (bench.py's ``host_only``/``device_only``/
``h2d_only``). This module computes the same style of breakdown from a
run's (or bench's) JSONL event stream, so a production train/predict
run is diagnosable with the exact vocabulary the bench artifacts use:
a host-bound vs device/transfer-bound vs pause-bound verdict.

Pure functions over parsed events — shared by ``tools/fmstat`` (CLI)
and tests; no jax import.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from fast_tffm_tpu.obs.registry import Histogram, MetricsRegistry
from fast_tffm_tpu.obs.sink import read_events

# Verdict thresholds over the train-loop time split. Above HOST_BOUND
# of loop wall spent waiting on the input pipeline, the host is the
# bottleneck (the bench's host_only ceiling binding); above PAUSE_BOUND
# in checkpoint/summary pauses, cadence knobs are. Otherwise the time
# is in dispatched device work + H2D, which host-side timing cannot
# split further — the verdict says so rather than guessing.
HOST_BOUND_FRACTION = 0.4
PAUSE_BOUND_FRACTION = 0.3


def _run_key(rec: Dict[str, Any]) -> tuple:
    run = rec.get("run") or {}
    return (run.get("pid"), run.get("process_index"),
            run.get("start_time"))


def summarize(paths: Sequence[str]) -> Dict[str, Any]:
    """Merge one or more metrics files (a run + its per-worker shards)
    into a single summary: final cumulative counters/hists folded
    across runs, gauges per process, scalars in arrival order."""
    last_metrics: Dict[tuple, Dict[str, Any]] = {}
    scalars: List[Dict[str, Any]] = []
    metas: List[Dict[str, Any]] = []
    health_events: List[Dict[str, Any]] = []
    crash_events: List[Dict[str, Any]] = []
    n_events = 0
    n_spans = 0
    run_starts = 0
    run_ends = 0
    for path in paths:
        # Health/crash state is scoped to each file's LATEST run: the
        # sink appends, so a fixed metrics path accumulates runs — an
        # old crash must not brand every later clean rerun CRASHED.
        # Each run_start resets the file-local view; the last segment
        # is what this file contributes.
        f_health: List[Dict[str, Any]] = []
        f_crash: List[Dict[str, Any]] = []
        f_started = 0
        f_ended = 0
        for rec in read_events(path):
            n_events += 1
            ev = rec.get("event")
            if ev == "metrics":
                # cumulative snapshots: the last one per run carries
                # everything before it
                last_metrics[_run_key(rec)] = rec
            elif ev == "scalar":
                scalars.append(rec)
            elif ev == "run_start":
                metas.append(rec.get("meta") or {})
                f_health, f_crash = [], []
                f_started, f_ended = 1, 0
            elif ev == "run_end":
                f_ended = 1
            elif ev == "health":
                f_health.append(rec)
            elif ev == "crash":
                f_crash.append(rec)
            elif ev == "span":
                n_spans += 1
        health_events.extend(f_health)
        crash_events.extend(f_crash)
        run_starts += f_started
        run_ends += f_ended

    merged = MetricsRegistry()
    gauges_by_proc: Dict[Any, Dict[str, float]] = {}
    for key, rec in last_metrics.items():
        for name, v in (rec.get("counters") or {}).items():
            merged.count(name, v)
        for name, s in (rec.get("hists") or {}).items():
            h = merged.histogram(name, bounds=s["bounds"])
            h.merge(Histogram.from_summary(s))
        proc = (rec.get("run") or {}).get("process_index", 0)
        for name, v in (rec.get("gauges") or {}).items():
            gauges_by_proc.setdefault(proc, {})[name] = v
    snap = merged.snapshot()
    # Flat gauge view: single-process reads naturally; multi-process
    # keeps the chief's values flat and everything per-process too.
    flat_gauges = dict(gauges_by_proc.get(0, {}))
    return {
        "meta": metas[0] if metas else {},
        "metas": metas,
        "runs": len(last_metrics),
        "events": n_events,
        "spans": n_spans,
        "run_starts": run_starts,
        "run_ends": run_ends,
        "health_events": health_events,
        "crash_events": crash_events,
        "counters": snap["counters"],
        "hists": snap["hists"],
        "gauges": flat_gauges,
        "gauges_by_process": gauges_by_proc,
        "scalars": scalars,
    }


def _frac(num: Optional[float], den: Optional[float]) -> Optional[float]:
    if not num or not den:
        return None
    return num / den


def wire_mode(gauges: Dict[str, Any]) -> Optional[str]:
    """The active wire format + dtype mode (README "Wire format") from
    the stream's ``wire/*`` gauges — ``"packed-narrow"`` etc. None on a
    pre-wire stream (no gauge): the mode is then unknown, not assumed
    padded, so old files never claim a mode they never stamped."""
    p = gauges.get("wire/packed")
    if p is None:
        return None
    fmt = "packed" if p else "padded"
    dt = "narrow" if gauges.get("wire/narrow") else "wide"
    return f"{fmt}-{dt}"


def attribution(summary: Dict[str, Any]) -> Dict[str, Any]:
    """The host/device/transfer split + verdict for one summary.

    Two sources, same table: a bench stream carries explicit ceiling
    gauges (``bench/host_only`` etc. — measured in isolation); a
    train/predict stream carries the loop-time split (input wait,
    pauses, step time) and the H2D byte rate.
    """
    c = summary.get("counters", {})
    g = summary.get("gauges", {})
    h = summary.get("hists", {})

    step = h.get("train/step_seconds") or {}
    loop_s = step.get("sum") or 0.0
    steps = c.get("train/steps") or step.get("count") or 0
    examples = c.get("train/examples", 0)
    input_wait = c.get("train/input_wait_seconds", 0.0)
    pauses = (c.get("train/checkpoint_pause_seconds", 0.0)
              + c.get("train/summary_pause_seconds", 0.0)
              + c.get("train/validation_seconds", 0.0))
    h2d_bytes = c.get("train/h2d_bytes", 0.0)
    # The wire-format pair (README "Wire format"): actual bytes
    # dispatched vs the padded layout's logical size — the
    # packed-vs-padded savings ratio, observable per run. Old streams
    # (pre-wire) carry no logical counter; treat it as equal.
    h2d_logical = c.get("train/h2d_bytes_logical", h2d_bytes)

    out: Dict[str, Any] = {
        "examples": examples,
        "steps": steps,
        "loop_seconds": loop_s,
        "examples_per_sec": _frac(examples, loop_s + pauses),
        "loop_examples_per_sec": _frac(examples, loop_s),
        "step_p50_s": step.get("p50"),
        "step_p99_s": step.get("p99"),
        "input_wait_fraction": _frac(input_wait, loop_s),
        "pause_seconds": pauses,
        "pause_fraction": _frac(pauses, loop_s + pauses),
        "h2d_bytes_per_sec": _frac(h2d_bytes, loop_s),
        # Bytes-per-example on the wire: the lever the packed format
        # pulls (ROADMAP item 2) — actual dispatched bytes, the padded
        # layout's logical bytes, and their ratio (>= 2x at the
        # default config is the packed acceptance bar).
        "h2d_bytes_per_example": _frac(h2d_bytes, examples),
        "h2d_logical_bytes_per_example": _frac(h2d_logical, examples),
        "wire_savings_ratio": _frac(h2d_logical, h2d_bytes),
        "wire_format": wire_mode(g),
        # Parallel host data plane (README "Data plane"): configured
        # build workers, their summed build seconds over the
        # consumer-observed build+wait time (values near the worker
        # count = the fan-out is real; near 1 = the plane added no
        # overlap), and the ordered ring's last-seen occupancy (full =
        # consumer-bound, empty = builders can't keep up).
        "host_threads": g.get("pipeline/host_threads"),
        "host_build_concurrency": _frac(
            c.get("pipeline/worker_build_seconds"),
            c.get("pipeline/build_seconds")),
        "ring_occupancy": g.get("pipeline/ring_occupancy"),
        "dedup_hit_rate": dedup_hit_rate(c),
        "padding_waste_fraction": padding_waste(c),
        "parse_errors": c.get("pipeline/parse_errors", 0),
        # Fault-tolerance accounting (README "Fault tolerance"): lines
        # skipped under bad_line_policy, and transient-IO retries paid.
        "bad_lines": c.get("pipeline/bad_lines", 0),
        "io_retries": c.get("io/retries", 0),
        # State-plane accounting (README "Checkpoint integrity &
        # fallback"): saves committed, restores that fell back past a
        # bad step, and step dirs quarantined (corrupt-<step>).
        "checkpoint_saves": c.get("checkpoint/saves", 0),
        "checkpoint_fallbacks": c.get("checkpoint/fallbacks", 0),
        "checkpoint_quarantined": c.get("checkpoint/quarantined_steps",
                                        0),
        # Compute-plane accounting (README "Elastic multi-host"):
        # peers that stopped heartbeating, elastic shrink recoveries,
        # and cluster bring-ups that exhausted their retry budget.
        "workers_lost": c.get("cluster/workers_lost", 0),
        "elastic_recoveries": c.get("cluster/elastic_recoveries", 0),
        "bringup_failures": c.get("cluster/bringup_failures", 0),
        # Streaming run mode (README "Streaming / online learning"):
        # discovery/seal/damage counters plus the freshness gauges the
        # STALE PUBLISH health verdict reads.
        "stream_files_discovered": c.get("stream/files_discovered", 0),
        "stream_files_sealed": c.get("stream/files_sealed", 0),
        "stream_truncated_files": c.get("stream/truncated_files", 0),
        "stream_deleted_files": c.get("stream/deleted_files", 0),
        "stream_publishes": c.get("stream/publishes", 0),
        "stream_publish_failures": c.get("stream/publish_failures", 0),
        "stream_watermark_lag_seconds": g.get(
            "stream/watermark_lag_seconds"),
        "stream_last_publish_age_seconds": g.get(
            "stream/last_publish_age_seconds"),
        "stream_publish_interval_seconds": g.get(
            "stream/publish_interval_seconds"),
        # Vocabulary admission (README "Unbounded vocabulary";
        # vocab_mode = admit): cumulative distinct-id observations and
        # how many of them hit the shared cold row, plus barrier
        # admission/eviction totals and the live-row/sketch gauges the
        # COLD-ROW SATURATION verdict reads.
        "vocab_ids": c.get("vocab/ids", 0),
        "vocab_cold_ids": c.get("vocab/cold_ids", 0),
        "vocab_cold_hit_rate": _frac(c.get("vocab/cold_ids"),
                                     c.get("vocab/ids")),
        "vocab_admitted": c.get("vocab/admitted_rows", 0),
        "vocab_evicted": c.get("vocab/evicted_rows", 0),
        "vocab_candidates_dropped": c.get("vocab/candidates_dropped",
                                          0),
        "vocab_live_rows": g.get("vocab/live_rows"),
        "vocab_sketch_fill": g.get("vocab/sketch_fill"),
        # Per-publish quality loop + gate (README "SLOs & quality
        # gate"; obs/quality.py): sweep count/cost, the latest quality
        # gauges, and how often the gate held the published pointer.
        "quality_evals": c.get("quality/evals", 0),
        "quality_eval_seconds": c.get("quality/eval_seconds", 0.0),
        "quality_examples": c.get("quality/examples", 0),
        "quality_gate_held": c.get("quality/gate_held", 0),
        "quality_auc": g.get("quality/auc"),
        "quality_loss": g.get("quality/loss"),
        "quality_calibration": g.get("quality/calibration"),
    }

    # Serving (README "Serving"; fast_tffm_tpu/serve/): request/latency
    # accounting plus the served-vs-published step pair the STALE MODEL
    # health verdict reads.
    lat = h.get("serve/request_latency_ms") or {}
    qd = h.get("serve/queue_depth") or {}
    out["serve_requests"] = c.get("serve/requests", 0)
    out["serve_examples"] = c.get("serve/examples", 0)
    out["serve_flushes"] = c.get("serve/flushes", 0)
    out["serve_flush_errors"] = c.get("serve/flush_errors", 0)
    out["serve_padded_examples"] = c.get("serve/padded_examples", 0)
    out["serve_reloads"] = c.get("serve/reloads", 0)
    out["serve_reload_failures"] = c.get("serve/reload_failures", 0)
    out["serve_latency_p50_ms"] = lat.get("p50")
    out["serve_latency_p99_ms"] = lat.get("p99")
    out["serve_queue_depth_p90"] = qd.get("p90")
    out["serve_served_step"] = g.get("serve/served_step")
    out["serve_published_step"] = g.get("serve/published_step")

    # Serving fleet (README "Serving fleet"; serve/fleet.py): the
    # supervisor's aggregate counts plus the proxy's routing
    # accounting — the FLEET render section and the FLEET DEGRADED
    # verdict read these.
    out["fleet_replicas"] = g.get("fleet/replicas")
    out["fleet_ready"] = g.get("fleet/ready")
    out["fleet_alive"] = g.get("fleet/alive")
    out["fleet_restarts"] = c.get("fleet/restarts", 0)
    out["fleet_reloads"] = c.get("fleet/reloads", 0)
    out["fleet_reload_failures"] = c.get("fleet/reload_failures", 0)
    out["proxy_requests"] = c.get("proxy/requests", 0)
    out["proxy_retries"] = c.get("proxy/retries", 0)
    out["proxy_shed_503"] = c.get("proxy/shed_503", 0)
    out["proxy_unrouted_503"] = c.get("proxy/unrouted_503", 0)
    out["proxy_canary_requests"] = c.get("proxy/canary_requests", 0)
    out["proxy_canary_score_delta"] = g.get("proxy/canary_score_delta")

    # Predict-path stats (a predict stream has no train loop at all;
    # both can coexist in one file — e.g. train-then-predict appends).
    p_ex = c.get("predict/examples", 0)
    p_s = c.get("predict/seconds", 0.0)
    depth = h.get("predict/fetch_depth") or {}
    out["predict_examples"] = p_ex
    out["predict_examples_per_sec"] = _frac(p_ex, p_s)
    out["predict_fetch_depth_p90"] = depth.get("p90")
    # Predict attribution (ISSUE 10 satellite): per-stage busy seconds
    # over the sweep wall — parse/build on the pipeline thread(s), D2H
    # bulk fetches (+ in-order delivery) on the fetch worker, score
    # writes on the writer thread. The stages OVERLAP by design (the
    # streaming scorer's whole point), so the shares are independent
    # utilizations that may sum past 1; the stage whose share
    # approaches 1 is the sweep's bound — a named verdict instead of
    # the old fetch-depth guess. predict/seconds is counted once per
    # sweep, so these are honest wall fractions — but ONLY on a
    # predict-only stream (loop_s == 0, the same gate the verdict
    # uses): a combined train-then-predict file feeds
    # pipeline/build_seconds and fetch/d2h_seconds from the train
    # loop and its validation sweeps too, which would inflate the
    # shares past any meaning.
    if p_s and p_ex and loop_s <= 0:
        out["predict_parse_share"] = _frac(
            c.get("pipeline/build_seconds"), p_s)
        out["predict_d2h_share"] = _frac(
            c.get("fetch/d2h_seconds"), p_s)
        out["predict_write_share"] = _frac(
            c.get("predict/write_seconds"), p_s)
    else:
        out["predict_parse_share"] = None
        out["predict_d2h_share"] = None
        out["predict_write_share"] = None

    # Bench ceilings, when the stream carries them (bench.py emits
    # these; a production run can be laid side by side with them).
    ceilings = {k.split("/", 1)[1]: v for k, v in g.items()
                if k.startswith("bench/")}
    if ceilings:
        out["ceilings"] = ceilings
        out["verdict"] = _bench_verdict(ceilings)
        return out

    iw = out["input_wait_fraction"]
    pf = out["pause_fraction"]
    if loop_s <= 0 and p_ex:
        out["verdict"] = _predict_verdict(out)
        return out
    if loop_s <= 0:
        out["verdict"] = "no train-loop data"
    elif iw is not None and iw > HOST_BOUND_FRACTION:
        # Host-parallel efficiency rides the host-bound verdict: a
        # host-bound run whose build concurrency already matches its
        # worker count needs MORE workers (or a faster parser); one
        # far below it has idle workers — a different fix.
        hp = ""
        ht = out.get("host_threads")
        conc = out.get("host_build_concurrency")
        if ht:
            hp = (f"; host_threads={ht:.0f}, build concurrency "
                  f"{conc:.1f}x" if conc is not None
                  else f"; host_threads={ht:.0f}")
        out["verdict"] = (f"host-bound: {iw:.0%} of the loop waits on "
                          f"the input pipeline{hp}")
    elif pf is not None and pf > PAUSE_BOUND_FRACTION:
        out["verdict"] = (f"pause-bound: {pf:.0%} of run time in "
                          "checkpoint/summary/validation pauses")
    else:
        # Name the active wire format + dtype mode in the
        # transfer-bound verdict: the first question at this verdict
        # is "how many bytes per example is the wire shipping, and is
        # the packed format on" (README "Wire format").
        wm = out.get("wire_format")
        wtag = f", wire {wm}" if wm else ""
        out["verdict"] = ("device/transfer-bound: the loop keeps the "
                          "dispatch stream full (host wait "
                          f"{iw:.0%}{wtag})" if iw is not None else
                          f"device/transfer-bound{wtag}")
    return out


# A predict stage whose busy share of the sweep wall exceeds this is
# named the bound; below it the sweep's time is in score dispatch +
# device compute, which host-side timing cannot split further.
PREDICT_STAGE_BOUND_FRACTION = 0.5

# Cold-row saturation floor (vocab_mode = admit): when more than this
# fraction of the run's distinct-id observations landed on the shared
# cold row, the table is too small for the stream's hot set — most of
# what the model sees trains one communal embedding. The VOCAB section
# names it and the fix (raise vocabulary_size, or lower
# vocab_admit_threshold so the hot set actually admits).
COLD_SATURATION_FRACTION = 0.5


def vocab_verdict(att: Dict[str, Any]) -> Optional[str]:
    """The VOCAB section's verdict line, or None while admission is
    healthy. Only meaningful on a stream that ran admission at all
    (vocab/ids > 0)."""
    rate = att.get("vocab_cold_hit_rate")
    if rate is None or not att.get("vocab_ids"):
        return None
    if rate > COLD_SATURATION_FRACTION:
        return (f"COLD-ROW SATURATION: {rate:.0%} of distinct-id "
                "observations hit the shared cold row — the hot set "
                "outgrew the table; raise vocabulary_size or lower "
                "vocab_admit_threshold")
    return None


def _predict_verdict(att: Dict[str, Any]) -> str:
    """Verdict for a predict-only stream, from the per-stage busy
    shares (parse / D2H / write over sweep wall — ISSUE 10): the stage
    saturating the wall is the bound, BY NAME. Streams without the
    stage counters (pre-refactor files) fall back to the fetch-depth
    heuristic: the output-order buffer (ChunkedFetcher) backs up
    exactly when D2H transfer lags scoring (BASELINE.md "Predict-path
    rate")."""
    rate = att.get("predict_examples_per_sec")
    base = (f"predict: {rate:,.0f} examples/sec over "
            f"{att['predict_examples']:,.0f} examples"
            if rate else "predict stream without rate data")
    stages = [(name, att.get(key)) for name, key in
              (("parse", "predict_parse_share"),
               ("d2h", "predict_d2h_share"),
               ("write", "predict_write_share"))]
    known = [(n, v) for n, v in stages if v is not None]
    if known:
        name, share = max(known, key=lambda kv: kv[1])
        detail = ", ".join(f"{n} {v:.0%}" for n, v in known)
        if share > PREDICT_STAGE_BOUND_FRACTION:
            return (base + f" — {name}-bound: {share:.0%} of the sweep "
                    f"wall is {name} ({detail})")
        return (base + " — score/dispatch-bound: no host stage "
                f"saturates the sweep ({detail})")
    p90 = att.get("predict_fetch_depth_p90")
    from fast_tffm_tpu.utils.fetch import FETCH_CHUNK_BATCHES
    if p90 is not None and p90 >= FETCH_CHUNK_BATCHES:
        return (base + " — transfer-bound: the output-order buffer "
                f"sits at {p90:.0f} batches (>= the {FETCH_CHUNK_BATCHES}"
                "-batch fetch chunk), scores wait on D2H")
    return base + " — host/scoring-bound (output-order buffer shallow)"


def _bench_verdict(ceil: Dict[str, float]) -> str:
    e2e = ceil.get("e2e")
    named = [(k, v) for k, v in ceil.items()
             if k in ("host_only", "device_only", "h2d_only") and v]
    if not e2e or not named:
        return "bench stream without e2e/ceiling gauges"
    # The binding constraint is the smallest ceiling; whichever ceiling
    # sits nearest the e2e number names the bottleneck (bench.py's
    # reading rule).
    name, v = min(named, key=lambda kv: abs(kv[1] - e2e))
    label = {"host_only": "host-bound",
             "device_only": "device-bound",
             "h2d_only": "transfer-bound"}[name]
    return (f"{label}: e2e {e2e:,.0f} ex/s tracks the {name} ceiling "
            f"({v:,.0f} ex/s)")


# Every `health: <kind>` event the codebase can emit, by status
# string. This is the read-side catalog: health_verdict maps each kind
# into a verdict or a detail note below, the README's health-event
# table documents each row, and fmlint R012 gates all three against
# the emit sites — a new health kind cannot land without its fmstat
# mapping and its catalog row.
HEALTH_KINDS = frozenset({
    "stalled", "recovered", "nonfinite_loss", "preempted",
    "worker_lost", "elastic_recovered", "ckpt_fallback", "bad_input",
    "collective_slow", "cluster_bringup_failed", "gate_held",
    "join_refused", "hbm_pressure",
})


def health_verdict(summary: Dict[str, Any]) -> Dict[str, Any]:
    """The run-health verdict line for one merged summary (obs/health):
    ``{"verdict": "OK" | "PREEMPTED" | "DEGRADED (N workers lost)" |
    "STALLED" | "NONFINITE" | "CRASHED", "detail": ...}``. Read purely
    from explicit stream events — severity order CRASHED > NONFINITE >
    PREEMPTED > DEGRADED > STALLED, because a crash ends the run while
    a survived stall merely delayed it; a preemption (train's SIGTERM/
    SIGINT save-and-exit path emits ``health: preempted``) is a CLEAN
    exit that must not read as a crash — the run saved, and a restart
    resumes it; and a DEGRADED run (``health: worker_lost`` diagnoses
    from the collective deadline guard, usually paired with
    ``elastic_recovered``) finished its work on a shrunken cluster —
    healed, but never silently green: the operator should know N
    workers' capacity is gone and the dead workers' shard streams end
    without a run_end. A run that RECOVERED from a bad checkpoint
    (``health: ckpt_fallback``) reads as ``OK (ckpt fallback xN)``. A
    stream that never wrote its run_end gets flagged in the detail
    either way (a hard-killed run writes no crash event; a live run
    hasn't finished — the reader knows which one it is holding)."""
    crashes = summary.get("crash_events") or []
    health = summary.get("health_events") or []
    stalls = [h for h in health if h.get("status") == "stalled"]
    fallbacks = [h for h in health
                 if h.get("status") == "ckpt_fallback"]
    recoveries = [h for h in health if h.get("status") == "recovered"]
    nonfin = [h for h in health
              if str(h.get("status", "")).startswith("nonfinite")]
    preempts = [h for h in health if h.get("status") == "preempted"]
    lost_events = [h for h in health
                   if h.get("status") == "worker_lost"]
    elastic = [h for h in health
               if h.get("status") == "elastic_recovered"]
    holds = [h for h in health if h.get("status") == "gate_held"]
    bad_inputs = [h for h in health if h.get("status") == "bad_input"]
    slow = [h for h in health
            if h.get("status") == "collective_slow"]
    bringup = [h for h in health
               if h.get("status") == "cluster_bringup_failed"]
    refused = [h for h in health
               if h.get("status") == "join_refused"]
    unclosed = (summary.get("run_starts", 0)
                > summary.get("run_ends", 0))
    notes = []
    if unclosed:
        notes.append("stream has no run_end (hard kill, still "
                     "running, or a lost worker's shard)")
    if bad_inputs:
        notes.append(f"{len(bad_inputs)} bad_input episode(s) — lines "
                     "skipped/quarantined under bad_line_policy")
    if slow:
        notes.append(f"{len(slow)} collective_slow episode(s) — the "
                     "cluster was healthy but slow at a deadline")
    if bringup:
        notes.append("cluster bring-up exhausted its retry budget "
                     "(cluster_bringup_failed)")
    if refused:
        notes.append(f"{len(refused)} join_refused event(s) — a "
                     "joiner was turned away at the grow rendezvous "
                     "(stale generation, or a slot race lost)")
    unknown = sorted({str(h.get("status", "")) for h in health}
                     - HEALTH_KINDS - {""})
    if unknown:
        notes.append(f"unrecognized health kind(s): "
                     f"{', '.join(unknown)} — update fmstat's catalog")
    if crashes:
        first = crashes[0]
        err = str(first.get("error", "?"))
        return {"verdict": "CRASHED",
                "detail": "; ".join(
                    [f"{len(crashes)} crash event(s); first: {err[:120]}"]
                    + notes)}
    if nonfin:
        names = sorted({str(h.get("name", "?")) for h in nonfin})
        lo = min((h.get("step_first") or 0) for h in nonfin)
        hi = max((h.get("step_last") or 0) for h in nonfin)
        return {"verdict": "NONFINITE",
                "detail": "; ".join(
                    [f"non-finite {', '.join(names)} over steps "
                     f"{lo}..{hi}"] + notes)}
    if preempts:
        last = preempts[-1]
        return {"verdict": "PREEMPTED",
                "detail": "; ".join(
                    [f"preemption signalled at step "
                     f"{last.get('step', '?')} (epoch "
                     f"{last.get('epoch', '?')}); the run saved and "
                     "exited cleanly — restart to resume"] + notes)}
    if lost_events:
        lost_ids = sorted(
            {int(p.get("process_index", -1))
             for h in lost_events for p in (h.get("lost") or [])}
            | {int(p) for h in elastic for p in (h.get("lost") or [])})
        n = max(len(lost_ids), 1)
        who = (", ".join(f"process {p}" for p in lost_ids)
               if lost_ids else "unnamed peer(s)")
        last_el = elastic[-1] if elastic else None
        cap = (last_el or {}).get("capacity")
        el_members = (last_el or {}).get("members") or []
        if last_el is not None and cap and len(el_members) == int(cap):
            # The LAST elastic event restored FULL membership (grow
            # healed the cluster, or every "lost" worker rejoined):
            # rendering DEGRADED here would be actively wrong — the
            # job finished at capacity. Never silently green though:
            # the healing story stays in the detail.
            gen = int(last_el.get("generation", 0))
            joined = sorted(int(p) for p in
                            (last_el.get("joined") or []))
            return {"verdict": f"RECOVERED (gen {gen}, "
                               f"{len(el_members)} workers)",
                    "detail": "; ".join(
                        [f"lost {who}, then elastic recovery x"
                         f"{len(elastic)} healed the cluster back to "
                         f"full membership ({len(el_members)}/"
                         f"{int(cap)} workers"
                         + (f", replacement(s) {joined} admitted"
                            if joined else "")
                         + f") — the run finished at capacity"]
                        + notes)}
        if elastic:
            gens = max(int(h.get("generation", 0)) for h in elastic)
            members = (elastic[-1].get("members") or [])
            how = (f"elastic shrink recovered x{len(elastic)} "
                   f"(generation {gens}, {len(members)} survivor(s)); "
                   "the run continued on the shrunken cluster")
        else:
            how = ("no elastic recovery recorded — the run failed "
                   "fast with the diagnosis (elastic = off) or was "
                   "still recovering")
        return {"verdict": f"DEGRADED ({n} worker"
                           f"{'s' if n != 1 else ''} lost)",
                "detail": "; ".join(
                    [f"collective deadline guard / heartbeat monitor "
                     f"lost {who}; {how}"] + notes)}
    if stalls:
        worst = max(float(h.get("stalled_seconds") or 0) for h in stalls)
        rec = (f", recovered x{len(recoveries)}" if recoveries
               else ", NOT recovered")
        return {"verdict": "STALLED",
                "detail": "; ".join(
                    [f"{len(stalls)} stall episode(s), worst "
                     f"{worst:.1f}s without progress{rec}; stacks: "
                     f"{stalls[0].get('stacks_file', '?')}"] + notes)}
    if holds:
        # Ranked below STALLED (the run itself is healthy — its DATA
        # or MODEL regressed) and above STALE PUBLISH (a long hold is
        # the usual cause of one; name the cause, not the symptom).
        last = holds[-1]
        why = "; ".join(last.get("reasons") or []) or \
            "validation quality regressed"
        return {"verdict": f"GATE-HELD (x{len(holds)})",
                "detail": "; ".join(
                    [f"publish gate held the pointer {len(holds)} "
                     f"time(s), last at step {last.get('step', '?')} "
                     f"(AUC {_fmt(last.get('auc'))}): {why}. Serving "
                     "continues on the last passing step; inspect the "
                     "input burst (quarantine sidecar, quality/auc "
                     "timeline) — publishes resume when validation "
                     "recovers"] + notes)}
    pressures = [h for h in health if h.get("status") == "hbm_pressure"]
    if pressures:
        # Ranked below DEGRADED/STALLED/GATE-HELD (the run is making
        # progress and its quality is fine — it is close to a capacity
        # wall) and above STALE PUBLISH (a pressured device is about
        # to become a failing reload/publish; name the cause first).
        last = pressures[-1]
        owners = last.get("owners") or {}
        top = (max(owners.items(), key=lambda kv: kv[1])
               if owners else None)
        top_note = (f"; largest owner {top[0]} "
                    f"({_fmt(top[1] / 2**20)} MB)" if top else "")
        return {"verdict": f"HBM-PRESSURE (x{len(pressures)})",
                "detail": "; ".join(
                    [f"{len(pressures)} pressure episode(s): live "
                     f"device bytes reached "
                     f"{_fmt(100 * float(last.get('fraction') or 0))}% "
                     f"of capacity (threshold "
                     f"{_fmt(100 * float(last.get('threshold') or 0))}"
                     f"%){top_note}. Size a fix before the OOM: python "
                     "-m tools.fmstat capacity <cfg> --what-if "
                     "vocabulary_size=...,dtype=f16,shards=K"] + notes)}
    deg = fleet_degraded(summary)
    if deg is not None:
        # Ranked above STALE PUBLISH / STALE MODEL: a fleet running
        # below strength is an availability incident NOW (one more
        # death may zero the ready set), while staleness is a
        # freshness problem — and a dead replica is often exactly why
        # a reload hasn't landed, so name the cause first.
        ready, total = deg
        return {"verdict": f"FLEET DEGRADED ({ready}/{total} ready)",
                "detail": "; ".join(
                    [f"{total - ready} of {total} serving replicas "
                     "not ready at the last flush — the proxy routes "
                     "around them while the supervisor restarts "
                     "(capped backoff) or drains a reload; check "
                     "fleet/restarts and the per-replica rows "
                     "(python -m tools.fmstat <supervisor metrics>)"]
                    + notes)}
    stale = stale_publish(summary)
    if stale is not None:
        # Checked BEFORE the unclosed-stream heuristic: a live stream
        # run legitimately has no run_end yet, and "the scorer is
        # being starved of fresh checkpoints" is the actionable
        # diagnosis there — a crashed stream run with no crash event
        # still reads STALE PUBLISH + the no-run_end note.
        age, interval = stale
        return {"verdict": "STALE PUBLISH",
                "detail": "; ".join(
                    [f"last published checkpoint is {age:.0f}s old, "
                     f"over 3x the {interval:.0f}s publish interval — "
                     "scorers are reloading stale state; check the "
                     "stream run's save/verify path"] + notes)}
    lag = stale_model(summary)
    if lag is not None:
        # Same placement rationale as STALE PUBLISH: a live serving
        # run legitimately has no run_end yet, and "the scorer is
        # serving older state than the pointer names" is the
        # actionable diagnosis — the reload loop is failing (verify
        # failures, a GC'd step, a dead watcher), not the publisher.
        served, published = lag
        return {"verdict": "STALE MODEL",
                "detail": "; ".join(
                    [f"serving checkpoint step {served:.0f} while the "
                     f"published pointer names step {published:.0f} — "
                     "the hot-reload loop is not landing; check "
                     "serve/reload_failures and the step's integrity "
                     "(python -m tools.fmckpt verify)"] + notes)}
    if unclosed:
        return {"verdict": "CRASHED", "detail": notes[0]}
    if fallbacks:
        steps = ", ".join(str(h.get("step", "?")) for h in fallbacks)
        quars = [h.get("quarantined") for h in fallbacks
                 if h.get("quarantined")]
        where = f"; quarantined: {quars[-1]}" if quars else ""
        return {"verdict": f"OK (ckpt fallback x{len(fallbacks)})",
                "detail": "; ".join(
                    [f"restore quarantined bad checkpoint step(s) "
                     f"{steps} and fell back to an older step — the "
                     f"run then completed cleanly{where}; reclaim "
                     "space with `python -m tools.fmckpt gc`"] + notes)}
    if notes:
        return {"verdict": "OK",
                "detail": "; ".join(["run_end present"] + notes)}
    return {"verdict": "OK", "detail": "no health/crash events; "
            "run_end present"}


def stale_publish(summary: Dict[str, Any]
                  ) -> Optional[Tuple[float, float]]:
    """(publish age, configured interval) when the stream run's last
    publish is older than STALE_PUBLISH_MULTIPLE x the interval at the
    final metrics flush, else None. Only meaningful for streams that
    publish (interval gauge present and > 0)."""
    g = summary.get("gauges", {})
    interval = g.get("stream/publish_interval_seconds")
    age = g.get("stream/last_publish_age_seconds")
    if not interval or age is None:
        return None
    if age > STALE_PUBLISH_MULTIPLE * interval:
        return float(age), float(interval)
    return None


# Publish-freshness ceiling, in intervals: past this the health verdict
# flips to STALE PUBLISH (the serving fleet is reloading old state).
STALE_PUBLISH_MULTIPLE = 3.0


def fleet_degraded(summary: Dict[str, Any]
                   ) -> Optional[Tuple[int, int]]:
    """(ready, total) when a fleet supervisor's last flush shows
    fewer ready replicas than the fleet size, else None. Only
    meaningful for fleet streams (the fleet/replicas gauge present) —
    the supervisor flushes eagerly on every ready-count edge, so a
    mid-incident snapshot carries the degradation window."""
    g = summary.get("gauges", {})
    total = g.get("fleet/replicas")
    ready = g.get("fleet/ready")
    if not total or ready is None:
        return None
    if ready < total:
        return int(ready), int(total)
    return None


def fleet_table(summary: Dict[str, Any]) -> List[str]:
    """Per-replica rows from the SUPERVISOR's gauges
    (``fleet/replica<i>_alive/_ready/_step/_queue_depth``): liveness
    and readiness split (the restart-vs-route distinction), the step
    each replica serves (a stagger or canary in flight shows as a
    step spread), and its admission-queue depth at the last flush."""
    g = summary.get("gauges", {})
    idx = sorted({int(k.split("_", 1)[0][len("fleet/replica"):])
                  for k in g
                  if k.startswith("fleet/replica")
                  and k.split("_", 1)[0][len("fleet/replica"):]
                  .isdigit()})
    rows = []
    for i in idx:
        alive = g.get(f"fleet/replica{i}_alive")
        ready = g.get(f"fleet/replica{i}_ready")
        step = g.get(f"fleet/replica{i}_step")
        depth = g.get(f"fleet/replica{i}_queue_depth")
        flag = ("ready" if ready else
                ("alive" if alive else "DOWN"))
        rows.append(
            f"r{i}: {flag:<6} step={_fmt(step)} "
            f"queue={_fmt(depth)}")
    return rows


def stale_model(summary: Dict[str, Any]
                ) -> Optional[Tuple[float, float]]:
    """(served step, published step) when a serving stream's last
    flush shows the served checkpoint LAGGING the published pointer —
    the reload loop failed to land the new step — else None. Only
    meaningful for serve streams (both gauges present); a healthy
    server's final flush always has served == published."""
    g = summary.get("gauges", {})
    served = g.get("serve/served_step")
    published = g.get("serve/published_step")
    if served is None or published is None:
        return None
    if published > served:
        return float(served), float(published)
    return None


def dedup_hit_rate(counters: Dict[str, float]) -> Optional[float]:
    """Fraction of feature occurrences deduplicated away by the host
    unique pass (1 - uniq_rows/nnz). None in raw-ids mode (the unique
    set never exists host-side)."""
    nnz = counters.get("pipeline/feature_nnz")
    uniq = counters.get("pipeline/uniq_rows")
    if not nnz or uniq is None:
        return None
    return max(0.0, 1.0 - uniq / nnz)


def padding_waste(counters: Dict[str, float]) -> Optional[float]:
    """Fraction of shipped [B, L] feature slots that are padding."""
    slots = counters.get("pipeline/feature_slots")
    nnz = counters.get("pipeline/feature_nnz")
    if not slots:
        return None
    return max(0.0, 1.0 - (nnz or 0.0) / slots)


def worker_table(summary: Dict[str, Any]) -> List[str]:
    """Per-worker liveness rows (one line per process that published
    ``worker/*`` gauges — multi-process runs with the heartbeat lease
    on): last heartbeat age at the final flush, lockstep windows
    completed, and examples processed. A worker named lost by a
    ``health: worker_lost`` diagnosis is flagged LOST — its row
    freezes at whatever its shard file last flushed."""
    lost_ids = set()
    for h in summary.get("health_events") or []:
        if h.get("status") == "worker_lost":
            for p in h.get("lost") or []:
                # fmlint: disable=R001 -- parsed JSON event fields,
                # host values only (this is the offline read side)
                lost_ids.add(int(p.get("process_index", -1)))
        elif h.get("status") == "elastic_recovered":
            # fmlint: disable=R001 -- parsed JSON event fields
            lost_ids.update(int(p) for p in h.get("lost") or [])
            # A grow recovery re-admits a slot a shrink once lost:
            # events are read in stream order, so the replacement's
            # row (fresh heartbeats and all) drops the LOST flag.
            # fmlint: disable=R001 -- parsed JSON event fields
            lost_ids -= {int(p) for p in h.get("joined") or []}
    rows = []
    for proc in sorted(summary.get("gauges_by_process", {})):
        g = summary["gauges_by_process"][proc]
        if not any(k.startswith("worker/") for k in g):
            continue
        age = g.get("worker/heartbeat_age_seconds")
        age_s = ("-" if age is None or age < 0
                 else f"{age:.1f}s")
        flag = "  LOST" if proc in lost_ids else ""
        rows.append(
            f"p{proc}: hb age {age_s}  windows "
            f"{_fmt(g.get('worker/windows', 0))}  examples "
            f"{_fmt(g.get('worker/examples', 0))}{flag}")
    return rows


# The EFFICIENCY section's gauge surface (README "Step anatomy"): the
# per-process anatomy/* gauges telemetry.anatomy_gauges pre-aggregates
# at barrier flushes — phase seconds split into local work vs
# cross-rank coordination waits. The verdict here works from the JSONL
# alone; the straggler-wait vs transport split needs the trace replay
# (fmtrace --anatomy).
ANATOMY_LOCAL_PHASES = (
    ("input wait", "anatomy/input_wait_seconds"),
    ("host build", "anatomy/host_build_seconds"),
    ("h2d", "anatomy/h2d_seconds"),
    ("dispatch", "anatomy/dispatch_seconds"),
    ("window fill", "anatomy/window_fill_seconds"),
    ("d2h fetch", "anatomy/fetch_seconds"),
)
ANATOMY_WAIT_PHASES = (
    ("flags wait", "anatomy/flags_wait_seconds"),
    ("lockstep allgather", "anatomy/allgather_seconds"),
)


def efficiency_table(summary: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Per-worker efficiency rows from the pre-aggregated anatomy/*
    gauges: efficiency = the fraction of step wall NOT parked in
    cross-rank coordination waits (flags allgather + lockstep
    allgather). None when no process published coordination waits
    (single-process runs, anatomy off, or pre-anatomy streams) — the
    section only exists where there is a cluster to explain. The
    straggler is the rank that waits LEAST: everyone else's wait is
    time spent waiting for it."""
    ranks: Dict[Any, Dict[str, Any]] = {}
    for proc in sorted(summary.get("gauges_by_process") or {}):
        g = summary["gauges_by_process"][proc]
        wall = g.get("anatomy/step_wall_seconds")
        if not wall:
            continue
        wait = sum(g.get(key) or 0.0 for _, key in ANATOMY_WAIT_PHASES)
        if wait <= 0:
            continue
        phases = {label: g.get(key) or 0.0
                  for label, key in (ANATOMY_LOCAL_PHASES
                                     + ANATOMY_WAIT_PHASES)}
        ex = g.get("anatomy/examples") or 0.0
        ranks[proc] = {
            "wall_seconds": wall,
            "wait_seconds": wait,
            "wait_fraction": wait / wall,
            "efficiency": max(0.0, 1.0 - wait / wall),
            "examples_per_sec": (ex / wall) if wall else None,
            "phases": phases,
        }
    if not ranks:
        return None
    straggler = min(ranks, key=lambda p: ranks[p]["wait_fraction"])
    wall_tot = sum(r["wall_seconds"] for r in ranks.values())
    wait_tot = sum(r["wait_seconds"] for r in ranks.values())
    wait_frac = wait_tot / wall_tot if wall_tot else 0.0
    local = {label: v
             for label, v in ranks[straggler]["phases"].items()
             if label not in dict(ANATOMY_WAIT_PHASES)}
    dom = max(local, key=local.get) if any(local.values()) else None
    verdict = (f"collective wait {wait_frac:.0%} of step"
               + (f"; rank {straggler} is the straggler"
                  f" (its dominant local phase: {dom})"
                  if len(ranks) > 1 and dom else ""))
    return {
        "ranks": ranks,
        "straggler_rank": straggler if len(ranks) > 1 else None,
        "wait_fraction": wait_frac,
        "efficiency": max(0.0, 1.0 - wait_frac),
        "verdict": verdict,
    }


def memory_table(summary: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Device-memory rows from the mem/* ledger gauges (obs/memory.py;
    chief view — the ledger is per-process and the flat gauges are
    process 0's). None for pre-ledger streams — the MEMORY section
    only exists where a ledger wrote gauges."""
    g = summary.get("gauges", {})
    if g.get("mem/live_bytes") is None and g.get("mem/peak_bytes") is None:
        return None
    totals = ("mem/live_bytes", "mem/peak_bytes", "mem/capacity_bytes",
              "mem/host_live_bytes", "mem/device_in_use_bytes")
    owners = {k[len("mem/"):-len("_bytes")]: v
              for k, v in g.items()
              if k.startswith("mem/") and k.endswith("_bytes")
              and k not in totals}
    return {
        "owners": owners,
        "live_bytes": g.get("mem/live_bytes"),
        "peak_bytes": g.get("mem/peak_bytes"),
        "host_live_bytes": g.get("mem/host_live_bytes"),
        "capacity_bytes": g.get("mem/capacity_bytes"),
        "utilization_fraction": g.get("mem/utilization_fraction"),
        "pressure_events":
            (summary.get("counters") or {}).get("mem/pressure_events"),
        "reload_peak_bytes": g.get("serve/reload_peak_bytes"),
    }


def _fmt(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if 0 < abs(v) < 0.01 or abs(v) >= 1e6:
            return f"{v:.3g}"
        return f"{v:,.3f}".rstrip("0").rstrip(".")
    return str(v)


def render(summary: Dict[str, Any]) -> str:
    """Human-readable attribution table for one merged summary — the
    fmstat output body."""
    att = attribution(summary)
    meta = summary.get("meta", {})
    lines = []
    head = [f"kind={meta.get('kind', '?')}",
            f"backend={meta.get('backend', '?')}",
            f"devices={meta.get('device_count', '?')}",
            f"processes={meta.get('process_count', '?')}",
            f"config={meta.get('config_hash', '?')}",
            f"git={meta.get('git_rev', '?')}"]
    lines.append("run: " + " ".join(head))
    lines.append(f"files merged: {summary.get('runs', 0)} run stream(s), "
                 f"{summary.get('events', 0)} events, "
                 f"{summary.get('spans', 0)} spans")
    hv = health_verdict(summary)
    lines.append(f"health: {hv['verdict']} — {hv['detail']}")
    lines.append("")
    rows = [
        ("examples", att["examples"]),
        ("steps", att["steps"]),
        ("examples/sec (incl pauses)", att["examples_per_sec"]),
        ("examples/sec (loop only)", att["loop_examples_per_sec"]),
        ("step p50 / p99 (s)",
         f"{_fmt(att['step_p50_s'])} / {_fmt(att['step_p99_s'])}"),
        ("input-wait fraction", att["input_wait_fraction"]),
        ("pause seconds (ckpt/summary/val)", att["pause_seconds"]),
        ("h2d bytes/sec", att["h2d_bytes_per_sec"]),
        ("h2d bytes/example (wire / padded)",
         f"{_fmt(att['h2d_bytes_per_example'])} / "
         f"{_fmt(att['h2d_logical_bytes_per_example'])}"),
        ("wire format (packed savings x)",
         f"{att['wire_format'] or '?'} "
         f"({_fmt(att['wire_savings_ratio'])})"),
        ("host threads / build concurrency",
         f"{_fmt(att['host_threads'])} / "
         f"{_fmt(att['host_build_concurrency'])}"),
        ("ring occupancy (last)", att["ring_occupancy"]),
        ("dedup hit rate", att["dedup_hit_rate"]),
        ("padding-waste fraction", att["padding_waste_fraction"]),
        ("parse errors", att["parse_errors"]),
        ("bad lines skipped", att["bad_lines"]),
        ("io retries", att["io_retries"]),
        ("checkpoint saves", att["checkpoint_saves"]),
        ("ckpt fallbacks / quarantined steps",
         f"{_fmt(att['checkpoint_fallbacks'])} / "
         f"{_fmt(att['checkpoint_quarantined'])}"),
        ("workers lost / elastic recoveries",
         f"{_fmt(att['workers_lost'])} / "
         f"{_fmt(att['elastic_recoveries'])}"),
    ]
    if att["predict_examples"]:
        rows += [
            ("predict examples", att["predict_examples"]),
            ("predict examples/sec",
             att["predict_examples_per_sec"]),
            ("predict fetch-depth p90 (batches)",
             att["predict_fetch_depth_p90"]),
            # Per-stage busy share of the sweep wall (stages overlap;
            # the one near 1.0 is the bound — see _predict_verdict).
            ("predict parse / d2h / write share",
             f"{_fmt(att['predict_parse_share'])} / "
             f"{_fmt(att['predict_d2h_share'])} / "
             f"{_fmt(att['predict_write_share'])}"),
        ]
    for k, v in rows:
        lines.append(f"  {k:<34} {_fmt(v)}")
    if att["stream_files_discovered"] or att[
            "stream_publish_interval_seconds"]:
        lines.append("  STREAMING (run_mode = stream):")
        age = att["stream_last_publish_age_seconds"]
        interval = att["stream_publish_interval_seconds"]
        for k, v in (
                ("watermark lag (s)",
                 att["stream_watermark_lag_seconds"]),
                ("files discovered / sealed",
                 f"{_fmt(att['stream_files_discovered'])} / "
                 f"{_fmt(att['stream_files_sealed'])}"),
                ("files truncated / deleted",
                 f"{_fmt(att['stream_truncated_files'])} / "
                 f"{_fmt(att['stream_deleted_files'])}"),
                ("publishes (failed)",
                 f"{_fmt(att['stream_publishes'])} "
                 f"({_fmt(att['stream_publish_failures'])})"),
                ("last publish age / interval (s)",
                 f"{_fmt(age)} / {_fmt(interval)}"),
        ):
            lines.append(f"    {k:<32} {v}")
    if att["quality_evals"] or att["quality_gate_held"]:
        lines.append("  QUALITY (per-publish eval + gate):")
        evs = att["quality_evals"]
        secs = att["quality_eval_seconds"]
        per = (secs / evs) if evs else None
        for k, v in (
                ("quality AUC (latest)", att["quality_auc"]),
                ("quality loss (latest)", att["quality_loss"]),
                ("calibration (pred/label)",
                 att["quality_calibration"]),
                ("evals (examples swept)",
                 f"{_fmt(evs)} ({_fmt(att['quality_examples'])})"),
                ("eval cost (s/eval)", per),
                ("publishes gate-held", att["quality_gate_held"]),
        ):
            lines.append(f"    {k:<32} {_fmt(v)}")
    if att["vocab_ids"] or att["vocab_live_rows"] is not None:
        lines.append("  VOCAB (vocab_mode = admit):")
        for k, v in (
                ("live rows", att["vocab_live_rows"]),
                ("admitted / evicted (barriers)",
                 f"{_fmt(att['vocab_admitted'])} / "
                 f"{_fmt(att['vocab_evicted'])}"),
                ("cold-row hit rate",
                 att["vocab_cold_hit_rate"]),
                ("sketch fill", att["vocab_sketch_fill"]),
                ("candidates dropped",
                 att["vocab_candidates_dropped"]),
        ):
            lines.append(f"    {k:<32} {_fmt(v)}")
        vv = vocab_verdict(att)
        if vv is not None:
            lines.append(f"    {vv}")
    if att["serve_requests"] or att["serve_served_step"] is not None:
        lines.append("  SERVING (run_tffm.py serve):")
        for k, v in (
                ("requests / examples",
                 f"{_fmt(att['serve_requests'])} / "
                 f"{_fmt(att['serve_examples'])}"),
                ("request latency p50 / p99 (ms)",
                 f"{_fmt(att['serve_latency_p50_ms'])} / "
                 f"{_fmt(att['serve_latency_p99_ms'])}"),
                ("micro-batch flushes (errors)",
                 f"{_fmt(att['serve_flushes'])} "
                 f"({_fmt(att['serve_flush_errors'])})"),
                ("padded examples (ladder waste)",
                 att["serve_padded_examples"]),
                ("queue depth p90",
                 att["serve_queue_depth_p90"]),
                ("hot reloads (failed)",
                 f"{_fmt(att['serve_reloads'])} "
                 f"({_fmt(att['serve_reload_failures'])})"),
                ("served / published step",
                 f"{_fmt(att['serve_served_step'])} / "
                 f"{_fmt(att['serve_published_step'])}"),
        ):
            lines.append(f"    {k:<32} {_fmt(v)}")
        hh = summary.get("hists") or {}
        stages = [hh.get(f"serve/{n}_ms") or {}
                  for n in ("queue_wait", "pad", "device", "reply")]
        if any(s.get("count") for s in stages):
            lines.append(
                f"    {'flush queue/pad/device/reply':<32} "
                + " / ".join(_fmt(s.get('p50')) for s in stages)
                + " ms (p50)")
    if att.get("fleet_replicas"):
        lines.append("  FLEET (serve --replicas):")
        for k, v in (
                ("replicas alive / ready / total",
                 f"{_fmt(att['fleet_alive'])} / "
                 f"{_fmt(att['fleet_ready'])} / "
                 f"{_fmt(att['fleet_replicas'])}"),
                ("restarts", att["fleet_restarts"]),
                ("staggered reloads (failed)",
                 f"{_fmt(att['fleet_reloads'])} "
                 f"({_fmt(att['fleet_reload_failures'])})"),
                ("proxy requests (retries)",
                 f"{_fmt(att['proxy_requests'])} "
                 f"({_fmt(att['proxy_retries'])})"),
                ("proxy 503s shed / unrouted",
                 f"{_fmt(att['proxy_shed_503'])} / "
                 f"{_fmt(att['proxy_unrouted_503'])}"),
        ):
            lines.append(f"    {k:<32} {_fmt(v)}")
        if att["proxy_canary_requests"] or \
                att["proxy_canary_score_delta"] is not None:
            lines.append(
                f"    {'canary requests / score delta':<32} "
                f"{_fmt(att['proxy_canary_requests'])} / "
                f"{_fmt(att['proxy_canary_score_delta'])}")
        for row in fleet_table(summary):
            lines.append(f"    {row}")
    mem = memory_table(summary)
    if mem:
        lines.append("  MEMORY (device ledger):")
        for name, v in sorted(mem["owners"].items(),
                              key=lambda kv: -(kv[1] or 0)):
            lines.append(f"    {name:<32} {_fmt(v / 2**20)} MB")
        live = mem["live_bytes"]
        peak = mem["peak_bytes"]
        lines.append(
            f"    {'live / peak (MB)':<32} "
            f"{_fmt(live / 2**20 if live is not None else None)} / "
            f"{_fmt(peak / 2**20 if peak is not None else None)}")
        cap = mem["capacity_bytes"]
        if cap:
            util = mem["utilization_fraction"]
            lines.append(
                f"    {'capacity (MB) / utilization':<32} "
                f"{_fmt(cap / 2**20)} / "
                f"{_fmt(util) if util is not None else '-'}")
        if mem["host_live_bytes"]:
            lines.append(f"    {'host-resident (MB)':<32} "
                         f"{_fmt(mem['host_live_bytes'] / 2**20)}")
        if mem["reload_peak_bytes"]:
            lines.append(f"    {'serve reload peak (MB)':<32} "
                         f"{_fmt(mem['reload_peak_bytes'] / 2**20)}")
        if mem["pressure_events"]:
            lines.append(f"    {'pressure episodes':<32} "
                         f"{_fmt(mem['pressure_events'])}")
    eff = efficiency_table(summary)
    if eff:
        lines.append("  EFFICIENCY (step anatomy):")
        for proc, r in eff["ranks"].items():
            top = sorted(((v / r["wall_seconds"], label)
                          for label, v in r["phases"].items() if v),
                         reverse=True)[:3]
            phases = ", ".join(f"{label} {frac:.0%}"
                               for frac, label in top)
            lines.append(
                f"    p{proc}: efficiency {r['efficiency']:.2f}  "
                f"wall {r['wall_seconds']:.1f}s  "
                f"rate {_fmt(r['examples_per_sec'])}/s  [{phases}]")
        lines.append(f"    {eff['verdict']}")
    worker_rows = worker_table(summary)
    if worker_rows:
        lines.append("  workers (per-process liveness):")
        for row in worker_rows:
            lines.append(f"    {row}")
    if "ceilings" in att:
        lines.append("  bench ceilings (examples/sec):")
        for k in ("e2e", "host_only", "device_only", "h2d_only"):
            if k in att["ceilings"]:
                lines.append(f"    {k:<32} "
                             f"{_fmt(att['ceilings'][k])}")
    lines.append("")
    lines.append(f"verdict: {att['verdict']}")
    return "\n".join(lines)
