"""Prometheus text-exposition rendering of a registry snapshot.

The serving front end's ``GET /metrics`` (README "Serving"): the obs
registry's counters/gauges/histograms in the exposition format
(version 0.0.4) a Prometheus scraper consumes directly — no JSONL
parsing on the scrape path, no extra bookkeeping on the serve path
(the snapshot is the same one /healthz reads). Dependency-free and
jax-free like the registry itself.

Naming: ``serve/request_latency_ms`` -> ``fm_serve_request_latency_ms``
(slashes and other non-metric characters fold to ``_``; everything is
prefixed ``fm_``). Histograms render the full convention — cumulative
``_bucket{le=...}`` series from the registry's fixed upper bounds, an
explicit ``+Inf`` bucket, ``_sum`` and ``_count`` — so quantiles are
the scraper's ``histogram_quantile`` over exact bucket counts, not a
re-quantization of our estimates.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict

# Content-Type the HTTP front end serves this under.
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def metric_name(name: str, prefix: str = "fm_") -> str:
    """A registry metric name as a legal Prometheus metric name."""
    return prefix + _NAME_BAD.sub("_", name)


def _num(v: float) -> str:
    """Exposition-format number: integers bare, floats via repr
    (shortest round-trip), non-finite as Prometheus spells them."""
    v = float(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v.is_integer() and abs(v) < 2 ** 53:
        return str(int(v))
    return repr(v)


def prometheus_text(snapshot: Dict[str, Any],
                    prefix: str = "fm_") -> str:
    """One scrape body from a ``MetricsRegistry.snapshot()`` dict.
    Deterministic (sorted names) so the format can be pinned by
    tests."""
    lines = []
    for name, v in sorted((snapshot.get("counters") or {}).items()):
        m = metric_name(name, prefix)
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {_num(v)}")
    for name, v in sorted((snapshot.get("gauges") or {}).items()):
        m = metric_name(name, prefix)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {_num(v)}")
    for name, s in sorted((snapshot.get("hists") or {}).items()):
        m = metric_name(name, prefix)
        lines.append(f"# TYPE {m} histogram")
        cum = 0
        for bound, count in zip(s["bounds"], s["counts"]):
            # fmlint: disable=R001 -- snapshot values are host
            # ints/floats (the registry is jax-free by design)
            cum += int(count)
            lines.append(f'{m}_bucket{{le="{_num(bound)}"}} {cum}')
        # fmlint: disable=R001 -- host snapshot value, never a device
        # array (offline read side)
        lines.append(f'{m}_bucket{{le="+Inf"}} {int(s["count"])}')
        lines.append(f"{m}_sum {_num(s['sum'])}")
        # fmlint: disable=R001 -- host snapshot value (see above)
        lines.append(f"{m}_count {int(s['count'])}")
    return "\n".join(lines) + "\n"
