"""Declarative SLOs over the metrics stream (README "SLOs & quality
gate").

A run's config declares its service-level objectives in the ``[SLO]``
section (``slo_publish_staleness_seconds`` / ``slo_p99_ms`` /
``slo_min_auc`` / ``slo_max_bad_fraction``; 0 = objective unset). The
spec is stamped into the run's metrics stream as ``slo/*`` gauges at
telemetry creation (train) and server startup (serve), so the
read-side needs NOTHING but the JSONL:

    python -m tools.fmstat slo <metrics.jsonl> [worker shards ...]

renders one PASS/FAIL row per configured objective — measured value
beside the bound — plus an overall verdict, and exits non-zero on any
FAIL (the closed-loop soak's assertion surface, and a scriptable
health check for deployments). Objectives with no supporting data in
the stream render SKIP, never a silent pass.

Everything here is pure functions over the ``attribution.summarize``
dict — no jax import, shared by the CLI, the soak, and tests.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional

# Gauge-name prefix the spec is stamped under (one gauge per set knob).
SLO_GAUGE_PREFIX = "slo/"

# The [SLO] knob fields, in render order.
_FIELDS = ("publish_staleness_seconds", "p99_ms", "min_auc",
           "max_bad_fraction")


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One run's declared objectives; 0 = that objective is unset."""

    publish_staleness_seconds: float = 0.0
    p99_ms: float = 0.0
    min_auc: float = 0.0
    max_bad_fraction: float = 0.0

    @classmethod
    def from_config(cls, cfg) -> "SloSpec":
        return cls(
            publish_staleness_seconds=float(
                getattr(cfg, "slo_publish_staleness_seconds", 0.0)),
            p99_ms=float(getattr(cfg, "slo_p99_ms", 0.0)),
            min_auc=float(getattr(cfg, "slo_min_auc", 0.0)),
            max_bad_fraction=float(
                getattr(cfg, "slo_max_bad_fraction", 0.0)))

    @classmethod
    def from_summary(cls, summary: Dict[str, Any]) -> "SloSpec":
        """Recover the spec a run stamped into its stream (the slo/*
        gauges). Merged multi-file summaries keep the chief's flat
        gauges, so a train + serve file pair folds into one spec."""
        g = summary.get("gauges", {})
        # fmlint: disable=R001 -- parsed JSON gauges, host floats only
        return cls(**{f: float(g.get(SLO_GAUGE_PREFIX + f, 0.0) or 0.0)
                      for f in _FIELDS})

    @property
    def empty(self) -> bool:
        return all(getattr(self, f) <= 0 for f in _FIELDS)

    def emit_gauges(self, reg) -> None:
        """Stamp the configured objectives into a metrics registry (or
        RunTelemetry — anything with ``set``). Unset objectives emit
        nothing: absence IS the unset marker at read time."""
        for f in _FIELDS:
            v = getattr(self, f)
            if v > 0:
                # fmlint: disable=R001 -- config floats, host-only
                reg.set(SLO_GAUGE_PREFIX + f, float(v))


@dataclasses.dataclass(frozen=True)
class SloResult:
    """One objective's verdict row."""

    objective: str          # human label
    bound: str              # e.g. "<= 5"
    measured: Optional[float]
    status: str             # "PASS" | "FAIL" | "SKIP"
    detail: str


def measured_publish_staleness(summary: Dict[str, Any]
                               ) -> Optional[float]:
    """Age of the last successful publish at the final metrics flush
    (the same gauge the STALE PUBLISH verdict reads)."""
    return summary.get("gauges", {}).get(
        "stream/last_publish_age_seconds")


def measured_p99_ms(summary: Dict[str, Any]) -> Optional[float]:
    """Serving request-latency p99 from the merged histogram."""
    h = summary.get("hists", {}).get("serve/request_latency_ms")
    return None if not h else h.get("p99")


def measured_auc(summary: Dict[str, Any]) -> Optional[float]:
    """Latest model-quality AUC: the publish-gate quality sweep's
    gauge, falling back to the plain validation gauge for runs without
    the per-publish loop."""
    g = summary.get("gauges", {})
    auc = g.get("quality/auc")
    return auc if auc is not None else g.get("validation/auc")


def measured_bad_fraction(summary: Dict[str, Any]) -> Optional[float]:
    """Bad lines over the input stream's good lines. The denominator
    prefers ``train/examples`` (lines actually trained) over the raw
    pipeline counter: ``pipeline/examples`` also counts every
    validation sweep's batches — and a gated stream sweeps validation
    at EVERY publish, which would dilute the fraction and mask a real
    ``slo_max_bad_fraction`` violation on the training stream. A
    stream with no traffic has no denominator — SKIP, not a free
    pass."""
    c = summary.get("counters", {})
    bad = c.get("pipeline/bad_lines", 0.0) or 0.0
    good = (c.get("train/examples", 0.0)
            or c.get("pipeline/examples", 0.0) or 0.0)
    if good + bad <= 0:
        return None
    return bad / (good + bad)


def evaluate_slos(spec: SloSpec,
                  summary: Dict[str, Any]) -> List[SloResult]:
    """One result row per CONFIGURED objective (unset objectives don't
    render — an empty spec yields an empty list). NaN measurements
    FAIL: an undefined quality number must never pass a quality
    bound."""
    rows: List[SloResult] = []

    def row(objective, threshold, measured, minimum=False, unit=""):
        if threshold <= 0:
            return
        op = ">=" if minimum else "<="
        bound = f"{op} {threshold:g}{unit}"
        if measured is None:
            rows.append(SloResult(objective, bound, None, "SKIP",
                                  "no supporting data in the stream"))
            return
        m = float(measured)
        if math.isnan(m):
            ok = False
        elif minimum:
            ok = m >= threshold
        else:
            ok = m <= threshold
        rows.append(SloResult(
            objective, bound, m, "PASS" if ok else "FAIL",
            f"measured {m:g}{unit}"))

    row("publish staleness", spec.publish_staleness_seconds,
        measured_publish_staleness(summary), unit="s")
    row("serve latency p99", spec.p99_ms, measured_p99_ms(summary),
        unit="ms")
    row("validation AUC", spec.min_auc, measured_auc(summary),
        minimum=True)
    row("bad-line fraction", spec.max_bad_fraction,
        measured_bad_fraction(summary))
    return rows


def overall(results: List[SloResult]) -> str:
    """"PASS" when every configured objective passed (SKIPs noted but
    not failing — the table shows them), "FAIL" on any failure,
    "EMPTY" when nothing was configured."""
    if not results:
        return "EMPTY"
    return "FAIL" if any(r.status == "FAIL" for r in results) else "PASS"


def render_slo(spec: SloSpec, results: List[SloResult]) -> str:
    """The `fmstat slo` table body."""
    lines = []
    if not results:
        return ("no SLO objectives configured: set [SLO] knobs "
                "(slo_publish_staleness_seconds / slo_p99_ms / "
                "slo_min_auc / slo_max_bad_fraction) on the run, or "
                "pass --config <file>")
    lines.append(f"{'SLO':<24} {'bound':<12} {'measured':<12} verdict")
    for r in results:
        measured = "-" if r.measured is None else f"{r.measured:g}"
        lines.append(f"{r.objective:<24} {r.bound:<12} {measured:<12} "
                     f"{r.status}")
    n_fail = sum(1 for r in results if r.status == "FAIL")
    n_skip = sum(1 for r in results if r.status == "SKIP")
    lines.append("")
    lines.append(f"overall: {overall(results)} ({len(results)} "
                 f"objective(s), {n_fail} failed, {n_skip} skipped)")
    return "\n".join(lines)


def results_json(spec: SloSpec,
                 results: List[SloResult]) -> Dict[str, Any]:
    """The `fmstat slo --json` payload."""
    return {
        "spec": dataclasses.asdict(spec),
        "objectives": [dataclasses.asdict(r) for r in results],
        "overall": overall(results),
    }
