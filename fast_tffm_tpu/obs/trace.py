"""Span timeline tracing over the telemetry JSONL stream.

``span("train/step")`` brackets one stage of a run and emits a ``span``
event (wall-clock start, duration, thread) into the active run's sink.
The aggregate metrics (obs/registry) say a run is slow; spans say where
a SPECIFIC step's time went — and because they ride the same JSONL
stream as everything else, ``tools/fmtrace`` can replay a whole run
(all worker shards, one track per process, one row per thread) in
ui.perfetto.dev.

Cost discipline — the same one as ``telemetry.active()``:

- no active run, or ``trace_spans`` off (the default): ``span()`` is
  ONE module-global read + one attribute read, and returns a shared
  ``contextlib.nullcontext`` — no allocation, nothing timed. Hot loops
  may therefore call it unconditionally (and fmlint R003 pushes them
  to, instead of hand-rolled ``perf_counter`` pairs).
- tracing on: two clock reads plus one buffered ``sink.emit`` per
  span. Host values only — a span can NEVER cause a device fetch, so
  enabling tracing preserves the zero-mid-stream-fetch contract
  (pinned by tests/test_health_trace.py).

Spans nest by time containment: Perfetto draws an inner span inside
its enclosing one when both ran on the same (pid, tid) track, so no
explicit parent ids are needed — the thread name IS the track.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, Optional

from fast_tffm_tpu.obs import telemetry as _telemetry

# Shared no-op context: nullcontext instances are stateless and
# reentrant, so every inactive span() returns this one object.
_NULL = contextlib.nullcontext()


def span(name: str, **fields):
    """Context manager timing one stage into the active run's stream.

    ``fields`` (step/epoch/path/...) land verbatim on the span event.
    Two field names are a cross-rank JOIN CONTRACT, not free-form
    annotations (obs/anatomy.py; README "Step anatomy"): ``step`` is
    the global step id and ``wid`` the lockstep window id — every rank
    stamps the same id onto the spans of the same barrier'd step/window
    (the collective protocol guarantees the sequences match), so
    ``fmtrace --anatomy`` can align per-rank clocks on the matched
    release edges and split a collective wait into straggler-wait vs
    transport. Producers gate the stamping on ``anatomy_on()``.

    Returns a shared no-op when no run is active or the run was not
    created with ``trace_spans`` — the default-off cost at every
    instrumented site is one module-global read."""
    tel = _telemetry.active()
    if tel is None or not getattr(tel, "trace_spans", False):
        return _NULL
    return _Span(tel.sink, name, fields or None)


def anatomy_on() -> bool:
    """Whether the active run wants step-anatomy join keys stamped
    (the ``anatomy`` config knob, default on). Same cost discipline as
    ``span()``: one module-global read + one attribute read, so hot
    producers may call it per window/step."""
    tel = _telemetry.active()
    return tel is not None and getattr(tel, "anatomy", False)


class _Span:
    """One live span: wall start at enter, duration at exit, emitted as
    a single buffered host-value event. ``perf_counter`` for the
    duration (monotonic), ``time.time`` for the start (the cross-
    process alignment fmtrace needs to line worker tracks up)."""

    __slots__ = ("_sink", "_name", "_fields", "_wall", "_t0")

    def __init__(self, sink, name: str,
                 fields: Optional[Dict[str, Any]]):
        self._sink = sink
        self._name = name
        self._fields = fields

    def __enter__(self) -> "_Span":
        self._wall = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.perf_counter() - self._t0
        rec = {"name": self._name, "ts": self._wall, "dur": dur,
               "tid": threading.current_thread().name}
        if self._fields:
            rec.update(self._fields)
        if exc_type is not None:
            # A span cut by an exception is exactly the one forensics
            # wants flagged on the timeline.
            rec["error"] = exc_type.__name__
        self._sink.emit("span", rec)
        return False
