"""Per-run telemetry wiring: registry + sink + the active-run lookup.

Drivers (train/predict/bench) create a ``RunTelemetry`` from the config
(``make_telemetry``) and run their loops under ``activate(tel)``;
instrumented library code (data pipeline, lockstep sharded path, C++
parser wrapper) calls ``active()`` and does nothing when no run is
active — so the default-off cost at every instrumented site is one
module-global read, and no signature anywhere grows a telemetry
parameter.

Multi-process: every process gets its own sink file — process 0 writes
``metrics_file`` itself, process p > 0 writes ``<metrics_file>.p<p>``
(same shared-filesystem assumption checkpoints already make) — with
the process index stamped into the run metadata of every event. The
streams merge at read time (``tools/fmstat`` accepts several files and
folds them through the registry's merge rules), not at run time: a
run-time merge would need a cross-process collective on the hot path.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import time
from typing import Any, Dict, Optional

from fast_tffm_tpu.obs.registry import MetricsRegistry
from fast_tffm_tpu.obs.sink import JsonlSink

_ACTIVE: Optional["RunTelemetry"] = None


def active() -> Optional["RunTelemetry"]:
    """The run telemetry instrumented library code should feed, or None
    (the common, zero-cost case)."""
    return _ACTIVE


def push_active(tel: Optional["RunTelemetry"]):
    """Install ``tel`` as the process-wide active telemetry; returns
    the previous value for ``pop_active``. The non-contextmanager form
    exists for drivers whose try/finally spans hundreds of lines —
    re-indenting the whole train loop under a ``with`` would be worse
    than a push in setup and a pop in the existing finally."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = tel
    return prev


def pop_active(prev: Optional["RunTelemetry"]) -> None:
    global _ACTIVE
    _ACTIVE = prev


@contextlib.contextmanager
def activate(tel: Optional["RunTelemetry"]):
    """Make ``tel`` the process-wide active telemetry for the body.
    None passes through (callers don't need their own conditional)."""
    if tel is None:
        yield None
        return
    prev = push_active(tel)
    try:
        yield tel
    finally:
        pop_active(prev)


def config_hash(cfg) -> str:
    """Stable short hash of the full config — two JSONL files with the
    same hash measured the same run shape."""
    import dataclasses
    d = dataclasses.asdict(cfg)
    blob = json.dumps(d, sort_keys=True, default=str).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()[:12]


def _git_rev() -> Optional[str]:
    import os
    import subprocess
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5)
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:
        return None  # telemetry must never block a run on git


def run_meta(cfg, kind: str, process_index: Optional[int] = None,
             process_count: Optional[int] = None) -> Dict[str, Any]:
    """Run metadata stamped into every metrics event: config hash,
    backend, device/process topology, git rev. ``process_index`` /
    ``process_count`` override jax's view — the train driver creates
    telemetry BEFORE the cluster join (so bring-up failures land in
    the stream), when jax would still claim a 1-process local world on
    every worker; the launcher-assigned task index and the config's
    worker count are the stable identities. (backend/device_count are
    the pre-join LOCAL view in that case; the driver refreshes the
    meta dict in place once the cluster is up, so metrics events
    carry the real topology.)"""
    import os
    import jax
    return {
        "kind": kind,
        "config_hash": config_hash(cfg) if cfg is not None else None,
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
        "process_index": (jax.process_index() if process_index is None
                          else int(process_index)),
        "process_count": (jax.process_count() if process_count is None
                          else int(process_count)),
        "git_rev": _git_rev(),
        "pid": os.getpid(),
        "start_time": time.time(),
    }


class RunTelemetry:
    """One run's registry + sink + flush cadence.

    ``maybe_flush(step)`` writes a metrics event every ``flush_steps``
    steps — host values only, zero device fetches. ``barrier_flush``
    (epoch boundaries, close) additionally bulk-fetches buffered device
    scalars, the only point device arrays are materialized.
    """

    def __init__(self, path: str, meta: Dict[str, Any],
                 flush_steps: int = 0, trace_spans: bool = False,
                 protocol_trace: bool = False,
                 watchdog_stall_seconds: float = 0.0,
                 anatomy: bool = True,
                 mem_pressure_fraction: float = 0.0):
        self.registry = MetricsRegistry()
        self.sink = JsonlSink(path, meta=meta)
        self.flush_steps = max(0, int(flush_steps))
        self._last_flush = time.perf_counter()
        self._closed = False
        # Span tracing (obs/trace.py): span() reads this flag through
        # active(), so the off cost at every site stays one global read.
        self.trace_spans = bool(trace_spans)
        # Collective-protocol tracing (parallel/liveness.py):
        # guarded_collective reads this through active() the same way.
        self.protocol_trace = bool(protocol_trace)
        # Step anatomy (obs/anatomy.py; README "Step anatomy"): gates
        # the window/step join-key stamping at the producers (train,
        # sharded) and the pre-aggregated anatomy/* phase gauges every
        # flush derives from host counters below — near-zero cost, and
        # NEVER a device fetch (pinned by tests/test_anatomy.py).
        self.anatomy = bool(anatomy)
        # HBM pressure threshold (obs/memory.py; README "Memory
        # observability"): fraction of device capacity at which a
        # flush emits health: hbm_pressure (once per episode). 0
        # disables; also inert when the backend reports no capacity.
        self.mem_pressure_fraction = float(mem_pressure_fraction or 0.0)
        # Compute-plane liveness (parallel/liveness.py): the train/
        # predict drivers attach their HeartbeatLease here so every
        # metrics flush carries per-worker liveness gauges (the fmstat
        # worker table) without the registry growing a liveness import.
        self.lease = None
        # Run-health watchdog (obs/health.py): a daemon thread fed by
        # heartbeat(); owns the stall/stack-dump forensics.
        self.watchdog = None
        if watchdog_stall_seconds and watchdog_stall_seconds > 0:
            from fast_tffm_tpu.obs.health import Watchdog
            self.watchdog = Watchdog(
                self.sink, watchdog_stall_seconds,
                stacks_path=path + ".stacks").start()

    # -- registry passthroughs (the instrumented-site surface) ----------
    def count(self, name: str, n: float = 1.0) -> None:
        self.registry.count(name, n)

    def set(self, name: str, v: float) -> None:
        self.registry.set(name, v)

    def observe(self, name: str, v: float, bounds=None) -> None:
        self.registry.observe(name, v, bounds)

    def add_scalar(self, name: str, step: int, value) -> None:
        """Buffer one (possibly device-array) scalar for the next
        barrier; never fetches here."""
        self.sink.add_scalar(name, step, value)

    def heartbeat(self, step: Optional[int] = None) -> None:
        """Touch the watchdog's progress beat — the train/predict loops
        call this once per step. No watchdog configured: one attribute
        read and out."""
        w = self.watchdog
        if w is not None:
            w.beat(step)

    def record_crash(self, exc: BaseException, step: int = -1) -> None:
        """Write the stream's final forensic event before the sink
        closes: exception type/message, traceback tail, and the ring of
        recent in-memory events (obs/sink.RING_EVENTS) — the "what was
        it doing just before" answer for a crashed run."""
        from fast_tffm_tpu.obs.health import format_crash
        recent = self.sink.recent_snapshot()
        self.sink.emit("crash", {
            "error": f"{type(exc).__name__}: {exc}",
            "traceback": format_crash(exc),
            "step": int(step),
            "recent_events": recent,
        })
        self.sink.flush()

    # -- flush cadence --------------------------------------------------
    def flush_due(self, step: int) -> bool:
        return bool(self.flush_steps) and step % self.flush_steps == 0

    def maybe_flush(self, step: int) -> None:
        if self.flush_due(step):
            self._emit_metrics(step)
            self.sink.flush()

    def barrier_flush(self, step: int) -> None:
        from fast_tffm_tpu.obs.trace import span
        self.heartbeat(step)  # a barrier IS progress — don't let a long
        # epoch-end fetch read as a stall
        with span("obs/barrier_flush", step=step):
            self._emit_metrics(step)
            self.sink.barrier()

    def _emit_metrics(self, step: int) -> None:
        now = time.perf_counter()
        self.registry.set("flush/window_seconds", now - self._last_flush)
        self._last_flush = now
        snap = self.registry.snapshot()
        lease = self.lease
        if lease is not None:
            # Per-worker liveness row (fmstat worker table): this
            # worker's own heartbeat age plus its share of the lockstep
            # work, as GAUGES — counters fold across processes at merge
            # time, gauges stay per-process (gauges_by_process).
            c = snap["counters"]
            age = lease.age()
            rows = {
                "worker/heartbeat_age_seconds":
                    round(age, 3) if age is not None else -1.0,
                "worker/windows": c.get("lockstep/windows", 0.0),
                "worker/examples": c.get("train/examples",
                                         c.get("predict/examples", 0.0)),
            }
            for k, v in rows.items():
                self.registry.set(k, v)
            snap["gauges"].update(rows)
        if self.anatomy:
            rows = anatomy_gauges(snap)
            for k, v in rows.items():
                self.registry.set(k, v)
            snap["gauges"].update(rows)
        # Device-memory ledger (obs/memory.py): per-owner bytes, live
        # total, peak watermark, capacity + utilization — pure host
        # arithmetic over registered owners, NEVER a device fetch
        # (pinned by tests/test_memory.py, same contract as anatomy).
        from fast_tffm_tpu.obs import memory as _mem
        rows = _mem.ledger_gauges()
        if rows:
            for k, v in rows.items():
                self.registry.set(k, v)
            snap["gauges"].update(rows)
            _mem.maybe_emit_pressure(self)
        self.sink.emit_metrics(step, snap)

    def close(self, step: int = -1) -> None:
        if self._closed:
            return
        self._closed = True
        if self.watchdog is not None:
            # Stop BEFORE the final emit/close: a watchdog firing into
            # a closing sink would race the file handle.
            self.watchdog.stop()
        if step >= 0:
            self._emit_metrics(step)
        else:
            self.sink.emit_metrics(-1, self.registry.snapshot())
        self.sink.close()

    # -- shared instrumentation helpers ---------------------------------
    def pipeline_batch(self, batch, pad_id: int,
                       build_seconds: Optional[float] = None) -> None:
        """Per-DeviceBatch pipeline counters: examples/lines, padding
        waste, dedup hit rate inputs, build time. Runs on the pipeline
        (prefetch worker) thread; everything here is host numpy."""
        import numpy as np
        B, L = batch.local_idx.shape
        self.count("pipeline/batches")
        self.count("pipeline/examples", batch.num_real)
        self.count("pipeline/example_capacity", B)
        if batch.uniq_ids is None:
            # raw-ids mode (dedup=device): pad cells hold pad_id
            # directly; the unique set is computed on device, so no
            # dedup-rate numerator exists host-side.
            real = int((batch.local_idx != pad_id).sum())
        else:
            real_uniq = int((batch.uniq_ids != pad_id).sum())
            real = int(
                (np.asarray(batch.uniq_ids)[batch.local_idx]
                 != pad_id).sum())
            self.count("pipeline/uniq_rows", real_uniq)
        self.count("pipeline/feature_slots", B * L)
        self.count("pipeline/feature_nnz", real)
        if build_seconds is not None:
            self.count("pipeline/build_seconds", build_seconds)
            self.observe("pipeline/batch_build_seconds", build_seconds)

    def train_step(self, dt: float, n_examples: int,
                   h2d_bytes: int,
                   h2d_bytes_logical: Optional[int] = None) -> None:
        """Per-train-step host-side points: wall time between step
        dispatches (NOT a device sync — the honest measurable without a
        fetch), examples, H2D payload bytes.

        ``h2d_bytes`` sizes the arrays ACTUALLY dispatched (the wire
        encoder's output — under wire_format = packed that is the flat
        CSR payload, not the padded rectangles); ``h2d_bytes_logical``
        sizes the padded layout the legacy wire would have shipped, so
        the packed-vs-padded savings ratio is observable per run
        (fmstat's bytes-per-example row). Omitted = same as actual
        (the padded wire)."""
        self.observe("train/step_seconds", dt)
        self.count("train/steps")
        self.count("train/examples", n_examples)
        self.count("train/h2d_bytes", h2d_bytes)
        self.count("train/h2d_bytes_logical",
                   h2d_bytes if h2d_bytes_logical is None
                   else h2d_bytes_logical)


# The step-anatomy phase map (README "Step anatomy"): cumulative
# host-side seconds counters -> per-process anatomy/* gauges. Counters
# fold across processes at merge time; the SAME numbers re-emitted as
# gauges stay per-process (gauges_by_process), which is what the fmstat
# EFFICIENCY section and bench --multihost need to rank stragglers.
# Everything here is a float already sitting in the snapshot dict —
# deriving the gauges can never add a device fetch.
ANATOMY_PHASES = {
    "anatomy/input_wait_seconds": "train/input_wait_seconds",
    "anatomy/host_build_seconds": "pipeline/build_seconds",
    "anatomy/h2d_seconds": "train/h2d_seconds",
    "anatomy/flags_wait_seconds": "train/step_flags_seconds",
    "anatomy/dispatch_seconds": "train/dispatch_seconds",
    "anatomy/window_fill_seconds": "lockstep/window_fill_seconds",
    "anatomy/allgather_seconds": "lockstep/allgather_seconds",
    "anatomy/fetch_seconds": "lockstep/fetch_seconds",
}


def anatomy_gauges(snap: Dict[str, Any]) -> Dict[str, float]:
    """This process's anatomy/* gauge rows for one registry snapshot:
    the phase-seconds counters above, plus the step wall and example
    totals the EFFICIENCY math divides by. Phases that never ticked are
    omitted (a predict run has no train/ rows and vice versa)."""
    c = snap.get("counters") or {}
    rows = {g: float(c[src]) for g, src in ANATOMY_PHASES.items()
            if c.get(src)}
    h = (snap.get("hists") or {}).get("train/step_seconds")
    if h and h.get("count"):
        rows["anatomy/step_wall_seconds"] = float(h["sum"])
        rows["anatomy/steps"] = float(h["count"])
    ex = c.get("train/examples", c.get("predict/examples", 0.0))
    if ex:
        rows["anatomy/examples"] = float(ex)
    return rows


def resolve_metrics_path(cfg,
                         process_index: Optional[int] = None
                         ) -> Optional[str]:
    """The JSONL path this process should write, or None when metrics
    are off. ``metrics_file = auto`` follows the sibling-artifact
    convention (<model_file>.tb/, <model_file>.ckpt/):
    <model_file>.metrics.jsonl. Non-chief processes get a .p<i> shard
    suffix so P workers never interleave writes in one file.
    ``process_index`` overrides jax's view (see run_meta) — and stays
    the worker's ORIGINAL index across elastic re-ranks, so one worker
    writes one shard file for the whole run."""
    path = getattr(cfg, "metrics_file", "") or ""
    if not path:
        return None
    if path == "auto":
        path = cfg.model_file + ".metrics.jsonl"
    if process_index is None:
        import jax
        process_index = jax.process_index()
    p = int(process_index)
    return path if p == 0 else f"{path}.p{p}"


def make_telemetry(cfg, kind: str,
                   process_index: Optional[int] = None,
                   process_count: Optional[int] = None
                   ) -> Optional[RunTelemetry]:
    """The driver entry point: a RunTelemetry per the config's metrics
    knobs, or None (the default — metrics_file unset)."""
    path = resolve_metrics_path(cfg, process_index=process_index)
    if path is None:
        return None
    # getattr defaults: tests (and bench) build pared-down cfg objects
    # that predate the tracing/watchdog knobs.
    return RunTelemetry(
        path, meta=run_meta(cfg, kind, process_index=process_index,
                            process_count=process_count),
        flush_steps=cfg.metrics_flush_steps,
        trace_spans=getattr(cfg, "trace_spans", False),
        protocol_trace=getattr(cfg, "protocol_trace", False),
        watchdog_stall_seconds=getattr(cfg, "watchdog_stall_seconds",
                                       0.0),
        anatomy=getattr(cfg, "anatomy", True),
        mem_pressure_fraction=getattr(cfg, "mem_pressure_fraction",
                                      0.0))


def batch_payload_bytes(args: Dict[str, Any]) -> int:
    """Host-side H2D payload size for one batch's arg dict — the
    arrays ACTUALLY about to be dispatched, so callers must pass the
    wire encoder's output, not the padded batch layout (under
    wire_format = packed the two differ by the padding-waste factor,
    and sizing the padded dict here is exactly how train/h2d_bytes and
    fmstat's transfer-bound attribution would silently lie). No device
    interaction."""
    n = 0
    for v in args.values():
        nb = getattr(v, "nbytes", None)
        if nb is not None:
            n += nb  # a plain int attribute on numpy arrays — no fetch
    return int(n)
