"""Device-memory ledger + capacity planner (README "Memory
observability").

The bytes axis of the observability stack: PR 1/2/12/17 cover
counters, spans, SLOs, and cross-rank time, but an oversized
``vocabulary_size`` still died as a raw XLA RESOURCE_EXHAUSTED with no
owner attribution, and a serve hot-reload transiently holds old+new
tables (a silent 2x spike). This module gives every long-lived device
allocation an OWNER:

- **Ledger** (``LEDGER``): each resident allocation the framework
  creates — the embedding table, the Adagrad accumulator, the wire
  double-buffers, prefetched/in-flight batches, lockstep window
  arrays, serve's table and its old+new reload pair — registers with
  an owner tag and host-computed ``nbytes``. ``ledger_gauges()``
  derives the ``mem/*`` gauge rows every telemetry flush carries:
  per-owner bytes, live total, peak watermark, device capacity +
  utilization. Host-int arithmetic only — ZERO device fetches, the
  same contract ``anatomy_gauges`` keeps (pinned by
  tests/test_memory.py).
- **Seam** (``device_memory_stats``): the ONE place the runtime's
  ``memory_stats()`` is consulted (fmlint R018, the memory analogue of
  R013's one-encoder rule). ``FM_FAKE_HBM_BYTES`` injects a capacity
  for tests and the fmchaos ``oom-pressure`` scenario; a backend that
  reports no capacity (the CPU container) reports None and every
  capacity consumer — pre-flight, pressure, the planner's verdict —
  degrades to "unknown", never a fake number.
- **Pressure + forensics**: ``maybe_emit_pressure`` emits
  ``health: hbm_pressure`` ONCE per episode (Watchdog-style episode
  state: crossing ``mem_pressure_fraction`` fires, dropping back below
  re-arms) and ``oom_guard`` re-raises a dispatch-site
  RESOURCE_EXHAUSTED as ``HbmExhaustedError`` carrying the rendered
  per-owner ledger — an OOM names WHICH owner grew.
- **Planner** (``plan`` / ``fmstat capacity``): predicts
  table/accumulator/wire/serve-resident bytes against device capacity
  from config alone — with ``--what-if vocabulary_size=N,dtype=f16,
  shards=K`` overrides, so ROADMAP items 1 (sharded tables) and 4
  (quantized resident tables) can be sized before a line of
  sharding/quantization code is written. ``preflight_capacity`` is the
  same prediction as a fail-fast guard at train()/ScorerServer
  startup.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Any, Dict, Optional

# The injected-capacity seam (tests, fmchaos oom-pressure): when set,
# device_memory_stats() reports this many bytes as the capacity and
# the ledger's live total as bytes_in_use, regardless of backend —
# the only way to exercise the capacity paths in the CPU container.
FAKE_CAPACITY_ENV = "FM_FAKE_HBM_BYTES"

F32_BYTES = 4
# What-if dtype names -> bytes per element (ROADMAP item 4's f16/int8
# resident-table sizing rides these).
DTYPE_BYTES = {"f32": 4, "float32": 4, "bf16": 2, "f16": 2,
               "float16": 2, "int8": 1}


def table_bytes(cfg=None, *, rows: Optional[int] = None,
                dim: Optional[int] = None,
                dtype_bytes: int = F32_BYTES) -> int:
    """The one table/accumulator sizing formula (satellite of ISSUE
    18): ``rows * row_dim * 4`` previously lived as four ad-hoc copies
    (lookup's pinned alloc, train's two export-npz guards, wire's
    logical-bytes sum) that the planner could silently disagree with.
    ``rows`` defaults to ``cfg.num_rows`` (the runtime table); pass
    ``cfg.ckpt_rows`` for the 4096-aligned checkpoint layout the
    offload backends allocate, or explicit ``rows=``/``dim=`` where no
    config is in scope (lookup backends size from their own state)."""
    if rows is None:
        rows = cfg.num_rows
    if dim is None:
        dim = cfg.row_dim
    return int(rows) * int(dim) * int(dtype_bytes)


# --- the memory_stats seam (fmlint R018) -----------------------------------

def device_memory_stats() -> Optional[Dict[str, Any]]:
    """The one ``memory_stats()`` call site in the tree (fmlint R018).

    Returns the first local device's stats dict (``bytes_limit``,
    ``bytes_in_use``, ...) or None when the backend reports none. The
    CPU backend reports None by policy even where jax exposes host
    stats: "device memory" there IS host RAM, and a capacity verdict
    against it would brand every beyond-HBM offload config broken —
    capacity planning is an accelerator concern. ``FM_FAKE_HBM_BYTES``
    overrides everything (the test/chaos seam)."""
    env = os.environ.get(FAKE_CAPACITY_ENV, "")
    if env:
        return {"bytes_limit": int(env),
                "bytes_in_use": LEDGER.live_bytes()}
    try:
        import jax
        dev = jax.local_devices()[0]
        if dev.platform == "cpu":
            return None
        stats = dev.memory_stats()
    except Exception:  # noqa: BLE001 - no backend/device: unmeasured
        return None
    return stats or None


def device_capacity_bytes() -> Optional[int]:
    """Device capacity from the seam, or None when unmeasurable — a 0
    must mean a MEASURED zero, never "couldn't measure" (the same
    policy lookup.memory_report documents)."""
    stats = device_memory_stats()
    if not stats:
        return None
    limit = stats.get("bytes_limit")
    if not limit:
        return None
    return int(limit)


# --- ownership ledger ------------------------------------------------------

class MemoryLedger:
    """Per-process registry of long-lived allocations by owner tag.

    ``register`` upserts an owner's current bytes (host-computed by
    the caller — ``.nbytes`` is a plain int attribute, never a fetch);
    ``release`` drops it. ``host=True`` owners (the host-offload
    table/accumulator) are tracked and gauged but excluded from the
    DEVICE live total — pressure and OOM forensics reason about HBM,
    and the offload backends exist precisely to hold state outside it.
    Thread-safe: the serve reload thread and dispatcher update
    concurrently with the train loop's wire buffers."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._owners: Dict[str, int] = {}
        self._host_owners: Dict[str, int] = {}
        self._peak = 0
        self._in_pressure = False

    def register(self, owner: str, nbytes: int,
                 host: bool = False) -> None:
        with self._lock:
            book = self._host_owners if host else self._owners
            (self._owners if host else self._host_owners).pop(owner,
                                                              None)
            book[owner] = int(nbytes)
            live = sum(self._owners.values())
            if live > self._peak:
                self._peak = live

    def release(self, owner: str) -> None:
        with self._lock:
            self._owners.pop(owner, None)
            self._host_owners.pop(owner, None)

    def live_bytes(self) -> int:
        """Device-resident live total (host owners excluded)."""
        with self._lock:
            return sum(self._owners.values())

    def peak_bytes(self) -> int:
        with self._lock:
            return self._peak

    def owners(self) -> Dict[str, int]:
        """Device owners snapshot (copy)."""
        with self._lock:
            return dict(self._owners)

    def host_owners(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._host_owners)

    def begin_pressure_episode(self) -> bool:
        """True exactly once per episode: the first crossing arms it;
        further calls inside the episode return False."""
        with self._lock:
            if self._in_pressure:
                return False
            self._in_pressure = True
            return True

    def end_pressure_episode(self) -> None:
        with self._lock:
            self._in_pressure = False

    def reset(self) -> None:
        """Test/bench seam: forget every owner, the peak, and any open
        pressure episode (the ledger is process-global state)."""
        with self._lock:
            self._owners.clear()
            self._host_owners.clear()
            self._peak = 0
            self._in_pressure = False


LEDGER = MemoryLedger()


def ledger_gauges() -> Dict[str, float]:
    """The ``mem/*`` gauge rows for one telemetry flush: per-owner
    bytes, live total, peak watermark, and capacity + utilization
    where the seam provides one. Empty dict when nothing ever
    registered (pre-ledger streams and bare-registry tests stay
    byte-identical). Host arithmetic only — zero device fetches
    (pinned by tests/test_memory.py, the ``anatomy_gauges``
    contract)."""
    owners = LEDGER.owners()
    hosts = LEDGER.host_owners()
    peak = LEDGER.peak_bytes()
    if not owners and not hosts and not peak:
        return {}
    rows: Dict[str, float] = {}
    for name, v in owners.items():
        rows[f"mem/{name}_bytes"] = float(v)  # fmlint: disable=R001 -- ledger values are host ints, never device arrays
    for name, v in hosts.items():
        rows[f"mem/{name}_bytes"] = float(v)  # fmlint: disable=R001 -- ledger values are host ints, never device arrays
    live = float(sum(owners.values()))
    rows["mem/live_bytes"] = live
    rows["mem/peak_bytes"] = float(peak)
    if hosts:
        rows["mem/host_live_bytes"] = float(sum(hosts.values()))
    stats = device_memory_stats()
    if stats:
        cap = stats.get("bytes_limit")
        if cap:
            rows["mem/capacity_bytes"] = float(cap)
            rows["mem/utilization_fraction"] = live / float(cap)
        in_use = stats.get("bytes_in_use")
        if in_use is not None:
            rows["mem/device_in_use_bytes"] = float(in_use)
    return rows


def maybe_emit_pressure(tel) -> None:
    """``health: hbm_pressure`` — once per episode. Crossing
    ``mem_pressure_fraction`` of device capacity emits one event
    (owner breakdown attached) and counts ``mem/pressure_events``;
    dropping back below the threshold re-arms, exactly the Watchdog's
    stall-episode model. No-op when the knob is 0 (default) or the
    backend reports no capacity."""
    frac = float(getattr(tel, "mem_pressure_fraction", 0.0) or 0.0)
    if frac <= 0:
        return
    cap = device_capacity_bytes()
    if not cap:
        return
    live = LEDGER.live_bytes()
    ratio = live / float(cap)
    if ratio < frac:
        LEDGER.end_pressure_episode()
        return
    if not LEDGER.begin_pressure_episode():
        return
    tel.count("mem/pressure_events")
    tel.sink.emit("health", {
        "status": "hbm_pressure",
        "live_bytes": int(live),
        "capacity_bytes": int(cap),
        "fraction": round(ratio, 4),
        "threshold": frac,
        "owners": {k: int(v) for k, v in LEDGER.owners().items()},
    })
    tel.sink.flush()


# --- OOM forensics ---------------------------------------------------------

class HbmExhaustedError(RuntimeError):
    """A dispatch-site RESOURCE_EXHAUSTED re-raised with the rendered
    per-owner ledger attached: the OOM names which owner grew instead
    of an opaque XLA abort. Chains from the original error."""


def is_oom(e: BaseException) -> bool:
    """Whether ``e`` is the runtime's out-of-device-memory failure.
    Matched on the message, not the type: jaxlib's XlaRuntimeError
    moved modules across releases, and the status-code string is the
    stable part of the contract."""
    msg = str(e)
    return ("RESOURCE_EXHAUSTED" in msg
            or "Resource exhausted" in msg
            or isinstance(e, HbmExhaustedError))


def render_ledger() -> str:
    """The per-owner breakdown block an OOM wrap (and fmstat's MEMORY
    section) renders: owners sorted by size, live/peak, capacity where
    known."""
    owners = LEDGER.owners()
    lines = ["device-memory ledger (per-owner resident bytes):"]
    for name, v in sorted(owners.items(), key=lambda kv: -kv[1]):
        lines.append(f"  {name:<24} {_mb(v)}")
    for name, v in sorted(LEDGER.host_owners().items(),
                          key=lambda kv: -kv[1]):
        lines.append(f"  {name:<24} {_mb(v)} (host)")
    if not owners and not LEDGER.host_owners():
        lines.append("  (no owners registered)")
    lines.append(f"  {'live total':<24} {_mb(LEDGER.live_bytes())}")
    lines.append(f"  {'peak watermark':<24} {_mb(LEDGER.peak_bytes())}")
    cap = device_capacity_bytes()
    if cap:
        lines.append(f"  {'device capacity':<24} {_mb(cap)}")
    return "\n".join(lines)


@contextlib.contextmanager
def oom_guard(where: str):
    """Wrap one dispatch site (train step, score_batch, serve reload):
    RESOURCE_EXHAUSTED re-raises as HbmExhaustedError carrying the
    rendered ledger; everything else passes through untouched."""
    try:
        yield
    except HbmExhaustedError:
        raise  # an inner guard already attributed it
    except Exception as e:
        if not is_oom(e):
            raise
        raise HbmExhaustedError(
            f"device out of memory at {where}: {e}\n"
            f"{render_ledger()}\n"
            "size a fix before rerunning: python -m tools.fmstat "
            "capacity <cfg> --what-if vocabulary_size=...,dtype=f16,"
            "shards=K") from e


# --- capacity planner ------------------------------------------------------

def _mb(n) -> str:
    n = float(n)
    if n >= 1 << 30:
        return f"{n:,.0f} B ({n / (1 << 30):.2f} GB)"
    return f"{n:,.0f} B ({n / (1 << 20):.2f} MB)"


def parse_what_if(spec: str) -> Dict[str, Any]:
    """``--what-if vocabulary_size=1000000,dtype=f16,shards=4`` ->
    override dict. Numeric values parse as ints; ``dtype`` keeps its
    name (resolved against DTYPE_BYTES at plan time)."""
    out: Dict[str, Any] = {}
    if not spec:
        return out
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"--what-if entry {part!r} is not key=value")
        k, v = part.split("=", 1)
        k, v = k.strip(), v.strip()
        if k == "dtype":
            if v not in DTYPE_BYTES:
                raise ValueError(
                    f"--what-if dtype {v!r} unknown; one of "
                    f"{sorted(DTYPE_BYTES)}")
            out[k] = v
        else:
            out[k] = int(v)  # fmlint: disable=R001 -- CLI string parse, host-only
    return out


def plan(cfg, kind: str = "train",
         overrides: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Predicted resident device bytes per owner, from config alone —
    what ``fmstat capacity`` renders and ``preflight_capacity``
    enforces, cross-checked against the live ledger by a tier-1 test
    (within 10% for the default shapes).

    ``overrides`` (the --what-if surface): ``vocabulary_size``,
    ``factor_num``, ``field_num``, ``batch_size``,
    ``max_features_per_example`` take numeric overrides; ``dtype``
    resizes the resident table (ROADMAP item 4 — the Adagrad
    accumulator stays f32: the quantization frontier quantizes the
    serving/resident table, not the optimizer state); ``shards``
    divides the per-device table/accumulator share (ROADMAP item 1's
    row-sharded mesh).

    ``kind="train"``: table + accumulator + wire double-buffers (+
    prefetch window). With ``lookup = host`` the table/accumulator
    move to the host-owner list — they are exactly what the offload
    mode keeps OUT of device memory. ``kind="serve"``: the resident
    table plus the old+new reload transient headroom a hot reload
    needs (serve/server._load_step holds both until the swap)."""
    o = dict(overrides or {})
    vocab = int(o.get("vocabulary_size", cfg.vocabulary_size))
    k = int(o.get("factor_num", cfg.factor_num))
    field = int(o.get("field_num", getattr(cfg, "field_num", 0)))
    dim = (k * field + 1
           if getattr(cfg, "model_type", "fm") == "ffm" else k + 1)
    dtype = o.get("dtype", "f32")
    shards = max(1, int(o.get("shards", 1)))
    batch = int(o.get("batch_size", cfg.batch_size))
    feats = int(o.get("max_features_per_example",
                      cfg.max_features_per_example))
    rows = vocab + 1  # num_rows: + the shared padding row
    tbl = table_bytes(rows=rows, dim=dim,
                      dtype_bytes=DTYPE_BYTES[dtype])
    acc = table_bytes(rows=rows, dim=dim)  # optimizer state stays f32
    per_shard_tbl = -(-tbl // shards)
    per_shard_acc = -(-acc // shards)
    # Wire double-buffer: depth 2 of the worst-case flat payload
    # (indices i32 + values f32 per slot, + per-example lengths) — the
    # encoder registers the ACTUAL shipped bytes at run time; this is
    # the from-config ceiling.
    wire = 2 * (batch * feats * (4 + F32_BYTES) + batch * 4)
    owners: Dict[str, int] = {}
    host_owners: Dict[str, int] = {}
    if kind == "serve":
        owners["serve_table"] = per_shard_tbl
        owners["serve_reload_transient"] = per_shard_tbl
    else:
        if getattr(cfg, "lookup", "device") == "host":
            host_owners["offload_table"] = per_shard_tbl
            host_owners["offload_acc"] = per_shard_acc
        else:
            owners["table"] = per_shard_tbl
            owners["adagrad_acc"] = per_shard_acc
        owners["wire_buffers"] = wire
    total = sum(owners.values())
    cap = device_capacity_bytes()
    out: Dict[str, Any] = {
        "kind": kind,
        "overrides": o,
        "owners": owners,
        "host_owners": host_owners,
        "total_bytes": int(total),
        "capacity_bytes": cap,
    }
    if cap:
        out["utilization_fraction"] = total / float(cap)
        out["verdict"] = "EXCEEDS" if total > cap else "FITS"
    else:
        out["verdict"] = "UNKNOWN (backend reports no capacity)"
    return out


def render_plan(p: Dict[str, Any]) -> str:
    """The human form of one plan: per-owner predicted bytes, total,
    capacity verdict — the fmstat capacity body and the pre-flight
    error's breakdown."""
    lines = [f"capacity plan ({p['kind']})"
             + (f" what-if {p['overrides']}" if p["overrides"] else "")
             + ":"]
    for name, v in sorted(p["owners"].items(), key=lambda kv: -kv[1]):
        lines.append(f"  {name:<24} {_mb(v)}")
    for name, v in sorted(p["host_owners"].items(),
                          key=lambda kv: -kv[1]):
        lines.append(f"  {name:<24} {_mb(v)} (host-resident)")
    lines.append(f"  {'predicted device total':<24} "
                 f"{_mb(p['total_bytes'])}")
    cap = p.get("capacity_bytes")
    if cap:
        lines.append(f"  {'device capacity':<24} {_mb(cap)}")
        lines.append(f"  {'utilization':<24} "
                     f"{p['utilization_fraction']:.1%}")
    lines.append(f"verdict: {p['verdict']}")
    return "\n".join(lines)


def preflight_capacity(cfg, kind: str = "train") -> None:
    """Fail fast at train()/ScorerServer startup when the PREDICTED
    resident bytes exceed the device capacity — the planner's
    breakdown plus the exact what-if invocation to explore fixes,
    instead of an XLA OOM minutes into bring-up. No-op when the
    backend reports no capacity (the CPU container)."""
    p = plan(cfg, kind)
    cap = p.get("capacity_bytes")
    if not cap or p["total_bytes"] <= cap:
        return
    raise ValueError(
        f"predicted resident device memory for this config exceeds "
        f"the device capacity ({_mb(p['total_bytes'])} > {_mb(cap)}) "
        f"— refusing to start rather than OOM mid-bring-up.\n"
        f"{render_plan(p)}\n"
        "explore fixes with: python -m tools.fmstat capacity "
        "<your.cfg> --what-if vocabulary_size=...,dtype=f16,shards=K "
        "(ROADMAP items 1 and 4), or lookup = host for the beyond-HBM "
        "offload path")
