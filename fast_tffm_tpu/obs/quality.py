"""Per-publish model-quality evaluation + the publish gate.

The closed-loop layer (README "SLOs & quality gate"): a streaming
trainer with ``validation_files`` runs one validation sweep at every
publish settle — the synchronization point that already exists
(checkpoint save + manifest verify) — and the sweep's quality numbers
both land in the metrics stream (``quality/auc`` / ``quality/loss`` /
``quality/calibration`` gauges under a ``quality/eval`` span) and gate
the ``published`` pointer: when validation regressed past the
``publish_min_auc`` / ``publish_max_auc_drop`` thresholds the pointer
does NOT move, a ``health: gate_held`` event fires, and fmstat's
verdict reads GATE-HELD. A bad data burst can therefore never reach
serving — scorers keep hot-reloading the last PASSING step while the
trainer keeps consuming (and, once the data heals, a later publish
passes and the loop closes again).

Zero-added-fetch contract: ``QualityStats`` is fed the SAME host score
chunks the validation AUC update consumes (``train.evaluate`` passes
it into its ChunkedFetcher callback; the lockstep path folds its four
sums into the existing AUC-histogram allgather payload), so the
quality loop introduces no device fetch beyond the sweep's own D2H —
the same link-safety discipline as the rest of obs/.

Multi-host: every worker computes the same deterministic decision from
the same merged AUC, and the chief's decision is additionally
broadcast (``data/stream.broadcast_blob`` — identity single-process)
so the pointer move and the baseline update are broadcast-identical by
construction, never by coincidence.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

# Probability clip for the logistic log-loss: a saturated score must
# cost a large-but-finite loss, not an inf that poisons the mean.
LOGLOSS_EPS = 1e-7

# Payload width QualityStats contributes to the lockstep AUC merge
# (loss_sum, weight_sum, pred_sum, label_sum).
SUMS_WIDTH = 4


class QualityStats:
    """Mergeable accumulator for the per-publish quality gauges.

    ``update(scores, labels, weights)`` consumes the raw (pre-sigmoid)
    host score chunks the validation sweep already fetched; ``sums()``
    / ``load_sums()`` are the fixed-width merge surface the lockstep
    path ships inside its existing allgather payload."""

    def __init__(self, loss_type: str = "logistic"):
        self.loss_type = loss_type
        self.loss_sum = 0.0
        self.weight_sum = 0.0
        self.pred_sum = 0.0
        self.label_sum = 0.0

    def update(self, scores, labels, weights) -> None:
        # The scorer's own overflow-stable sigmoid (metrics.py) — a
        # saturated logit chunk must not spray exp-overflow warnings,
        # and the gate's probability must be THE serving probability.
        from fast_tffm_tpu.metrics import sigmoid
        s = np.asarray(scores, dtype=np.float64)
        y = np.asarray(labels, dtype=np.float64)
        w = np.asarray(weights, dtype=np.float64)
        if self.loss_type == "logistic":
            p = sigmoid(s)
            pc = np.clip(p, LOGLOSS_EPS, 1.0 - LOGLOSS_EPS)
            loss = -(y * np.log(pc) + (1.0 - y) * np.log(1.0 - pc))
        else:  # mse: the "prediction" is the raw score itself
            p = s
            loss = (s - y) ** 2
        self.loss_sum += float((w * loss).sum())
        self.weight_sum += float(w.sum())
        self.pred_sum += float((w * p).sum())
        self.label_sum += float((w * y).sum())

    def sums(self) -> np.ndarray:
        return np.asarray([self.loss_sum, self.weight_sum,
                           self.pred_sum, self.label_sum], np.float64)

    def load_sums(self, vals) -> None:
        """Replace the local sums with merged (cross-worker) totals —
        the tail of the lockstep AUC-merge payload."""
        vals = np.asarray(vals, dtype=np.float64).reshape(-1)
        if vals.shape[0] != SUMS_WIDTH:
            raise ValueError(
                f"quality sums payload must have {SUMS_WIDTH} values, "
                f"got {vals.shape[0]}")
        self.loss_sum, self.weight_sum, self.pred_sum, self.label_sum = (
            float(v) for v in vals)

    @property
    def loss(self) -> Optional[float]:
        """Weighted mean validation loss (log-loss for logistic, MSE
        for mse), or None on an empty sweep."""
        if self.weight_sum <= 0:
            return None
        return self.loss_sum / self.weight_sum

    @property
    def calibration(self) -> Optional[float]:
        """Sum(predicted) / sum(label) — 1.0 is perfectly calibrated,
        >1 over-predicts. None when the sweep held no positive mass
        (the ratio is undefined, not infinite)."""
        if self.label_sum <= 0:
            return None
        return self.pred_sum / self.label_sum


class PublishGate:
    """The per-publish quality gate's decision state.

    ``decide(auc, step)`` is PURE (no state mutation) and returns a
    JSON-safe decision dict, so the chief's decision can ride
    ``broadcast_blob`` verbatim and every worker applies the identical
    outcome; ``note_published(auc)`` advances the baseline only after
    a publish actually landed. On the very first publish no baseline
    exists yet, so only ``publish_min_auc`` applies — the documented
    first-publish contract."""

    def __init__(self, min_auc: float = 0.0, max_drop: float = 0.0):
        self.min_auc = float(min_auc)
        self.max_drop = float(max_drop)
        # AUC of the last SUCCESSFUL publish; None until one lands.
        self.baseline: Optional[float] = None

    @classmethod
    def from_config(cls, cfg) -> Optional["PublishGate"]:
        min_auc = float(getattr(cfg, "publish_min_auc", 0.0))
        max_drop = float(getattr(cfg, "publish_max_auc_drop", 0.0))
        if not min_auc and not max_drop:
            return None
        return cls(min_auc=min_auc, max_drop=max_drop)

    def decide(self, auc: float, step: int) -> Dict[str, Any]:
        auc = float(auc)
        reasons = []
        # A non-finite AUC (empty or single-class validation sweep)
        # HOLDS any configured gate outright — including a
        # max_drop-only gate on its very first publish, where neither
        # threshold comparison below would fire: an unevaluable model
        # must never publish through a gate.
        if not np.isfinite(auc):
            reasons.append(
                f"validation AUC is {auc} (empty or single-class "
                "sweep): a configured gate never passes an "
                "unevaluable model")
        if self.min_auc and not auc >= self.min_auc:
            reasons.append(
                f"AUC {auc:.6f} below publish_min_auc {self.min_auc}")
        if (self.max_drop and self.baseline is not None
                and not auc >= self.baseline - self.max_drop):
            reasons.append(
                f"AUC {auc:.6f} dropped {self.baseline - auc:.6f} from "
                f"the last published {self.baseline:.6f} "
                f"(publish_max_auc_drop {self.max_drop})")
        return {
            "held": bool(reasons),
            "step": int(step),
            "auc": auc,
            "baseline": self.baseline,
            "min_auc": self.min_auc,
            "max_auc_drop": self.max_drop,
            "reasons": reasons,
        }

    def note_published(self, auc: Optional[float]) -> None:
        """Record a LANDED publish's AUC as the next drop baseline.
        Non-finite values never become a baseline (a NaN baseline
        would disarm the drop check forever)."""
        if auc is not None and np.isfinite(auc):
            self.baseline = float(auc)


def emit_gate_held(tel, decision: Dict[str, Any]) -> None:
    """The gate's durable evidence: a ``health: gate_held`` event +
    ``quality/gate_held`` counter, flushed straight to disk — the
    stream keeps running, but the operator's fmstat view (and the
    soak's assertions) must see the hold NOW, not at the next barrier.
    No-op without telemetry."""
    if tel is None:
        return
    tel.count("quality/gate_held")
    tel.sink.emit("health", {
        "status": "gate_held",
        "step": int(decision.get("step", -1)),
        "auc": decision.get("auc"),
        "baseline": decision.get("baseline"),
        "reasons": list(decision.get("reasons") or []),
    })
    tel.sink.flush()


def emit_quality(tel, step: int, auc: float, stats: QualityStats,
                 n_examples: int, eval_seconds: float) -> None:
    """The sweep's metrics-side landing: gauges + counters + one
    timeline scalar, all plain host floats (the zero-added-fetch
    contract — everything here was computed from already-fetched score
    chunks). Sets ``validation/auc`` too: the quality sweep IS this
    stream's validation pass."""
    if tel is None:
        return
    tel.count("quality/evals")
    tel.count("quality/eval_seconds", float(eval_seconds))
    tel.count("quality/examples", float(n_examples))
    tel.set("quality/auc", float(auc))
    tel.set("validation/auc", float(auc))
    if stats.loss is not None:
        tel.set("quality/loss", float(stats.loss))
    if stats.calibration is not None:
        tel.set("quality/calibration", float(stats.calibration))
    # fmlint: disable=R001 -- auc is a host float from the streamed
    # AUC merge, never a device array
    tel.add_scalar("quality/auc", int(step), float(auc))
