"""Buffered JSONL event sink with ScalarSummaries' link-safety rules.

Events buffer in host memory and reach disk only at ``flush()`` —
called from the flush-step cadence and epoch barriers, never per step.
Device scalars (a jitted step's loss is a device array; materializing
it mid-stream stalls the async dispatch pipeline for seconds on a
tunnelled link — BASELINE.md "Device-link sync pathology") are buffered
AS DEVICE REFERENCES and bulk-fetched in ONE ``utils/fetch.bulk_fetch``
transfer at ``barrier()`` — the epoch-boundary call — with the same
1024-entry safety cap as ``train.LOG_BUFFER_MAX``. A plain ``flush()``
performs zero device fetches, so a mid-epoch flush cadence
(``metrics_flush_steps``) costs file I/O only.

Thread-safety: ``emit``/``flush``/``close`` serialize on one internal
lock — span events arrive from the prefetch and fetcher worker
threads, and health events from the watchdog thread, concurrently with
the driver's flush cadence. ``add_scalar``/``barrier`` stay
driver-thread-only (they are the device-reference path; see the
link-safety contract above).

The barrier drain is also the run-health seam for non-finite values
(obs/health.py): the loss scalars are ALREADY host-side right after
the one bulk fetch, so checking them there detects NaN/Inf loss with
zero added device fetches — a ``health`` event with the offending
name and step range rides the same stream.

Crash forensics: the last ``RING_EVENTS`` emitted events are kept in
an in-memory ring; the drivers' ``crash`` event embeds that ring, so
the stream's final line answers "what was the run doing just before
it died" even when everything since the last flush was lost.

One line per event, ``json.dumps``-encoded. ``metrics`` events carry
the run metadata dict every time ("one event per flush with run
metadata"), so any single line is attributable to its run without
scanning backwards for a header.
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

# Buffered device-scalar cap — the same bound (and rationale) as
# train.LOG_BUFFER_MAX / summaries.SUMMARY_BUFFER_MAX: a tiny cadence
# on a months-long epoch must not retain unbounded device scalars; one
# rare mid-epoch bulk sync is the lesser evil.
SCALAR_BUFFER_MAX = 1024

# Host-event buffer cap: spans at per-batch cadence with an epoch-only
# flush would otherwise grow the buffer for a whole epoch. Hitting the
# cap forces a plain flush — file I/O only, safe anywhere, any thread.
EVENT_BUFFER_MAX = 4096

# In-memory ring of recent events embedded in a crash event.
RING_EVENTS = 32


class JsonlSink:
    """Append-mode JSONL writer; see module docstring for the buffering
    and link-safety contract."""

    def __init__(self, path: str, meta: Optional[Dict[str, Any]] = None):
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self.path = path
        self.meta = dict(meta or {})
        self._lock = threading.Lock()
        self._events: List[str] = []
        self._scalars: List[Tuple[str, int, Any]] = []
        self.recent: "collections.deque" = collections.deque(
            maxlen=RING_EVENTS)
        self._fh = open(path, "a", encoding="utf-8")
        self._closed = False
        self._fh_closed = False
        self.emit("run_start", {"meta": self.meta})

    def emit(self, event: str, fields: Optional[Dict[str, Any]] = None
             ) -> None:
        """Queue one host-value event (no device arrays — those go
        through add_scalar). Buffered until flush(). Thread-safe: span/
        health events arrive from worker threads."""
        rec = {"event": event, "t": time.time()}
        if fields:
            rec.update(fields)
        line = json.dumps(rec, default=_json_default)
        overflow = False
        with self._lock:
            if self._fh_closed:
                # A late span from a never-joined daemon thread
                # (prefetch, fetcher) after run_end: drop it — writing
                # would raise on the closed handle in that thread.
                return
            self._events.append(line)
            self.recent.append(rec)
            overflow = len(self._events) >= EVENT_BUFFER_MAX
        if overflow:
            self.flush()  # host file I/O only — safe from any thread

    def recent_snapshot(self) -> List[Dict[str, Any]]:
        """A stable copy of the recent-event ring. Must take the lock:
        worker threads append concurrently, and iterating a mutating
        deque raises — which would lose the crash event exactly when
        it matters."""
        with self._lock:
            return list(self.recent)

    def emit_metrics(self, step: int, snapshot: Dict[str, Any]) -> None:
        """One metrics event per flush, run metadata included."""
        self.emit("metrics", {"step": int(step), "run": self.meta,
                              **snapshot})

    def add_scalar(self, name: str, step: int, value: Any) -> None:
        """Queue one scalar whose value may be a DEVICE array; it is
        not fetched here — barrier() bulk-fetches the whole buffer.
        Driver-thread-only (the device-reference path)."""
        self._scalars.append((name, int(step), value))
        if len(self._scalars) >= SCALAR_BUFFER_MAX:
            self._drain_scalars()

    def flush(self) -> None:
        """Write buffered events to disk. ZERO device fetches: queued
        device scalars stay queued until the next barrier()."""
        with self._lock:
            if self._fh_closed:
                self._events.clear()
                return
            events, self._events = self._events, []
            if events:
                self._fh.write("\n".join(events) + "\n")
            self._fh.flush()

    def _drain_scalars(self) -> None:
        if not self._scalars:
            return
        # ONE grouped-stacking transfer for the whole buffer (the same
        # entry point ScalarSummaries.flush and train.flush_log use).
        from fast_tffm_tpu.utils.fetch import bulk_fetch
        rows: List[Tuple[str, int, float]] = []
        bulk_fetch([(v, (name, step))
                    for name, step, v in self._scalars],
                   lambda v, m: rows.append(
                       (m[0], m[1], float(v))))  # host array post-fetch
        self._scalars.clear()
        bad: Dict[str, List[int]] = {}
        for name, step, val in rows:
            self.emit("scalar", {"name": name, "step": step, "value": val})
            # Only LOSS scalars escalate to a health event: a NaN
            # validation AUC is a legitimate value (a shard with no
            # positives or no negatives — StreamingAUC.result), and
            # flagging it would mark healthy runs NONFINITE. The raw
            # scalar event above still records it for forensics.
            if "loss" in name and not math.isfinite(val):
                bad.setdefault(name, []).append(step)
        # Non-finite detection rides the fetch that just happened: the
        # values are host floats here, so this costs zero extra device
        # traffic (obs/health.py's contract).
        for name, steps in bad.items():
            self.emit("health", {
                "status": "nonfinite_loss",
                "name": name,
                "step_first": min(steps), "step_last": max(steps),
                "count": len(steps),
            })

    def barrier(self) -> None:
        """Epoch/shutdown barrier: bulk-fetch queued device scalars into
        scalar events, then flush everything to disk."""
        self._drain_scalars()
        self.flush()

    def discard_scalars(self) -> int:
        """Drop queued device scalars WITHOUT fetching them — the
        compute-plane recovery path (parallel/liveness.py): after a
        peer dies, a buffered loss scalar may be the output of a
        collective program that will never complete, and draining it
        would park the survivor in the exact hang the deadline guard
        just escaped. Returns the number dropped (recorded by the
        caller's telemetry so the gap is visible, not silent)."""
        n = len(self._scalars)
        self._scalars.clear()
        return n

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            # Drain scalars BEFORE queueing run_end so the stream's
            # last event is always run_end (readers key "run finished
            # cleanly" off it).
            self._drain_scalars()
            self.emit("run_end", {})
            self.flush()
        finally:
            # Close the handle UNDER the lock and flag it first: a
            # worker-thread emit/flush racing this sequence sees the
            # flag and drops its event instead of writing to (or
            # overflowing into) a closed file.
            with self._lock:
                self._fh_closed = True
                self._fh.close()


def _json_default(o: Any):
    """Numpy scalars/arrays sneak into host-value events (counter sums,
    batch shapes); coerce rather than crash a telemetry flush."""
    for attr in ("item",):
        f = getattr(o, attr, None)
        if callable(f):
            try:
                return f()
            except Exception:  # fmlint: disable=R004 -- probing an
                # .item() coercion; a failure falls through to the
                # tolist/str fallbacks below, nothing is swallowed
                pass
    if hasattr(o, "tolist"):
        return o.tolist()
    return str(o)


def read_events(path: str) -> Iterator[Dict[str, Any]]:
    """Parse a metrics JSONL file (or a worker shard of one). Tolerates
    a torn final line — a crashed run's file must still summarize."""
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                continue  # torn tail of a crashed run
