"""Metrics registry: counters, gauges, fixed-bucket histograms.

Dependency-free by design (stdlib only): the registry is imported by
hot-path modules (data/pipeline.py, data/cparser.py) whose import cost
and thread model must stay trivial. Thread-safety contract: the
single-call forms (``count``/``set``/``observe``) and
``snapshot``/``merge`` all mutate/read under one registry lock — the
pipeline mutates from the prefetch worker thread while the train loop
snapshots from the main thread, so instrumented sites MUST use those
forms. The accessor forms (``counter()``/``gauge()``/``histogram()``)
hand back the raw metric object, whose methods are NOT locked — they
exist for single-threaded setup/tests and read-side tooling. Per-point
cost is a lock + dict lookup + float add, cheap enough for per-batch
(not per-line) cadence.

Histograms use FIXED bucket boundaries so two histograms from different
workers (or different flush windows) merge by adding bucket counts —
the property the sharded path's per-worker event streams rely on.
Quantiles are bucket-upper-bound estimates: exact enough to tell a 2 ms
step from a 200 ms stall, which is the job.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple


def default_time_buckets() -> Tuple[float, ...]:
    """Exponential seconds ladder, 100 us .. ~100 s: covers a 20 us TPU
    step rounded up through a multi-second tunnelled-link stall."""
    out, b = [], 1e-4
    while b < 200.0:
        out.append(b)
        b *= 2.0
    return tuple(out)


class Counter:
    """Monotonic accumulator (ints or float seconds/bytes)."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n


class Gauge:
    """Last-written value (rates, depths, AUC)."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Fixed-bucket histogram with sum/min/max and estimated quantiles.

    ``bounds`` are bucket UPPER bounds (ascending); an implicit overflow
    bucket catches everything past the last bound. ``merge`` requires
    identical bounds — guaranteed within a run because the registry
    owns bucket choice per metric name, and across workers because all
    workers run the same code.
    """

    __slots__ = ("bounds", "counts", "count", "sum", "min", "max")

    def __init__(self, bounds: Optional[Sequence[float]] = None) -> None:
        self.bounds: Tuple[float, ...] = tuple(
            bounds if bounds is not None else default_time_buckets())
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError(
                f"histogram bounds must be strictly increasing, "
                f"got {self.bounds}")
        self.counts: List[int] = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.sum += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile: the upper bound of the bucket holding
        the q-th point (min/max for the open ends). None when empty."""
        if not self.count:
            return None
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target and c:
                if i >= len(self.bounds):
                    return self.max
                return min(self.bounds[i],
                           self.max if self.max is not None
                           else self.bounds[i])
        return self.max

    def merge(self, other: "Histogram") -> None:
        if other.bounds != self.bounds:
            raise ValueError(
                "cannot merge histograms with different bounds: "
                f"{self.bounds} vs {other.bounds}")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        for attr, pick in (("min", min), ("max", max)):
            ov = getattr(other, attr)
            if ov is not None:
                sv = getattr(self, attr)
                setattr(self, attr, ov if sv is None else pick(sv, ov))

    def summary(self) -> Dict[str, object]:
        """JSON-ready fixed-quantile summary + the raw mergeable state
        (bounds/counts ride along so a reader can re-merge windows)."""
        mean = self.sum / self.count if self.count else None
        return {
            "count": self.count, "sum": self.sum, "mean": mean,
            "min": self.min, "max": self.max,
            "p50": self.quantile(0.50), "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
            "bounds": list(self.bounds), "counts": list(self.counts),
        }

    @classmethod
    def from_summary(cls, s: Dict[str, object]) -> "Histogram":
        """Inverse of ``summary()`` — fmstat re-merges flush windows and
        workers through the same merge() the live registry uses."""
        h = cls(bounds=s["bounds"])
        h.counts = list(s["counts"])
        h.count = int(s["count"])
        h.sum = float(s["sum"])
        h.min = s["min"]
        h.max = s["max"]
        return h


class MetricsRegistry:
    """Named metric store: get-or-create accessors, a consistent
    snapshot, and worker-merge. One lock serializes mutation against
    snapshot (prefetch thread vs driver thread)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            return g

    def histogram(self, name: str,
                  bounds: Optional[Sequence[float]] = None) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(bounds)
            return h

    # Single-call forms for instrumented sites: get-or-create AND
    # mutate under the lock, so a worker-thread point can never tear
    # against a concurrent snapshot() (see module docstring).
    def count(self, name: str, n: float = 1.0) -> None:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            c.inc(n)

    def set(self, name: str, v: float) -> None:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            g.set(v)

    def observe(self, name: str, v: float,
                bounds: Optional[Sequence[float]] = None) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(bounds)
            h.observe(v)

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """One JSON-ready dict: {"counters": {...}, "gauges": {...},
        "hists": {name: summary}}. Cumulative (not delta) — readers
        diff consecutive snapshots for windowed rates, so a dropped
        flush loses resolution, never mass."""
        with self._lock:
            return {
                "counters": {k: c.value
                             for k, c in self._counters.items()},
                "gauges": {k: g.value for k, g in self._gauges.items()
                           if g.value is not None},
                "hists": {k: h.summary()
                          for k, h in self._hists.items()},
            }

    def merge(self, other: "MetricsRegistry") -> None:
        """Fold another worker's registry in: counters add, histograms
        bucket-merge, gauges last-writer-wins (per-worker gauges should
        be namespaced by process index before merging)."""
        snap = other.snapshot()
        with self._lock:
            for k, v in snap["counters"].items():
                c = self._counters.get(k)
                if c is None:
                    c = self._counters[k] = Counter()
                c.inc(v)
            for k, v in snap["gauges"].items():
                g = self._gauges.get(k)
                if g is None:
                    g = self._gauges[k] = Gauge()
                g.set(v)
            for k, s in snap["hists"].items():
                h = self._hists.get(k)
                if h is None:
                    self._hists[k] = Histogram.from_summary(s)
                else:
                    h.merge(Histogram.from_summary(s))
