"""Cross-rank step anatomy: the clock-aligned critical-path profiler
(README "Step anatomy"; ``fmtrace --anatomy`` is the CLI).

``bench.py --multihost`` says a 2-process cluster runs at ~0.2x
per-worker efficiency; this module says WHERE the other 80% goes. The
telemetry stream already records every ingredient — per-rank ``span``
events (obs/trace.py), per-rank ``collective`` seq events
(parallel/liveness.py), lockstep counters — but each rank stamps spans
with its OWN clocks, so the streams cannot be compared directly. The
pipeline here:

1. **Clock alignment** (``align_clocks``): the collective protocol
   guarantees every rank posts the same barrier collectives in the
   same order (fmlint R014 statically, ``fmtrace --collectives`` at
   runtime), so the k-th occurrence of a barrier span name on every
   rank brackets the SAME barrier. All ranks leave a barrier at
   (nearly) the same true instant — the RELEASE edge (span end) is
   the sync point. Per rank we least-squares fit ``offset + drift``
   of its wall clock against rank 0 over all matched release edges.
   Accuracy is bounded by the release skew of the transport itself
   (the residual is reported; sub-millisecond on localhost gloo,
   looser over real networks — see the README caveats).

2. **Phase accounts** (``build_report``): per rank, span durations
   fold into named phases — host (input wait + batch build), H2D,
   step dispatch (async enqueue backpressure: the previous program
   still executing), lockstep window fill / score dispatch / D2H
   fetch — and every matched barrier's wait splits on the aligned
   clock into *straggler wait* (my arrival -> the last rank's
   arrival: waiting on a PEER) vs *transport* (last arrival ->
   release: waiting on the COLLECTIVE itself, which on CPU+gloo also
   absorbs the previous step's still-queued device program).

3. **Critical path** (``build_report`` -> ``render``): per-worker
   efficiency recomputed from the phases (the fraction of wall NOT
   parked in cross-rank coordination), the overlap fraction, a
   straggler ranking (which rank arrives last, how often, and its
   dominant phase — the "why"), and a one-line verdict naming the
   dominant phase of the slowest rank.

Pure functions over parsed JSONL events (no jax import) — shared by
the ``fmtrace --anatomy`` CLI and the synthetic-clock tests, exactly
like tools/fmtrace's converter. The pre-aggregated ``anatomy/*``
gauges the chief emits at barriers (obs/telemetry.anatomy_gauges) are
the no-trace-replay fallback fmstat's EFFICIENCY section reads; this
module is the full-resolution instrument.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from fast_tffm_tpu.obs.sink import read_events

# Barrier span names: every rank posts these in the same order (the
# collective protocol), so the k-th occurrence on every rank brackets
# the same barrier — the join that needs no stamped id (the stamped
# step/wid fields ride along for labeling and sanity checks).
BARRIER_SPANS = ("train/step_flags", "stream/step_flags",
                 "lockstep/allgather")

# Span name -> phase label for the per-rank duration accounts.
PHASE_SPANS = {
    "train/h2d": "h2d",
    "train/step": "step dispatch",
    "lockstep/window_fill": "window fill",
    "lockstep/score_dispatch": "score dispatch",
    "lockstep/score_fetch": "d2h fetch",
}

# Phases that are cross-rank coordination: time a rank would not pay
# running alone. Efficiency = 1 - coordination/wall.
WAIT_PHASES = ("straggler wait", "transport")


def events_by_rank(paths: Sequence[str]
                   ) -> Dict[int, List[Dict[str, Any]]]:
    """Parse metrics JSONL files into per-rank event lists, keyed by
    the process index each file's run_start announces (the same
    convention as tools/fmtrace). File order is emission order within
    a rank — the occurrence-index join relies on it."""
    out: Dict[int, List[Dict[str, Any]]] = {}
    for path in paths:
        pid = 0
        events: List[Dict[str, Any]] = []
        for rec in read_events(path):
            if rec.get("event") == "run_start":
                meta = rec.get("meta") or {}
                # fmlint: disable=R001 -- parsed JSON event field
                pid = int(meta.get("process_index") or 0)
            events.append(rec)
        out.setdefault(pid, []).extend(events)
    return out


def _barrier_edges(events: Sequence[Dict[str, Any]]
                   ) -> Dict[str, List[Tuple[float, float, Any]]]:
    """One rank's barrier spans, grouped by name in emission order:
    (start, end, stamped id) per occurrence. start/end are the rank's
    OWN wall clock (span ts / ts+dur)."""
    out: Dict[str, List[Tuple[float, float, Any]]] = {}
    for rec in events:
        if rec.get("event") != "span":
            continue
        name = rec.get("name")
        if name not in BARRIER_SPANS:
            continue
        # fmlint: disable=R001 -- parsed JSON event fields
        ts = float(rec.get("ts", rec.get("t", 0.0)))
        # fmlint: disable=R001 -- parsed JSON event fields
        dur = float(rec.get("dur", 0.0))
        out.setdefault(name, []).append(
            (ts, ts + dur, rec.get("step", rec.get("wid"))))
    return out


class ClockFit:
    """One rank's wall clock mapped onto rank 0's: aligned(t) =
    t + offset + drift * (t - t_ref). Rank 0 is the identity fit."""

    __slots__ = ("offset", "drift", "t_ref", "sync_points",
                 "residual_rms")

    def __init__(self, offset: float = 0.0, drift: float = 0.0,
                 t_ref: float = 0.0, sync_points: int = 0,
                 residual_rms: float = 0.0):
        self.offset = offset
        self.drift = drift
        self.t_ref = t_ref
        self.sync_points = sync_points
        self.residual_rms = residual_rms

    def aligned(self, t: float) -> float:
        return t + self.offset + self.drift * (t - self.t_ref)


def _fit(pairs: Sequence[Tuple[float, float]]) -> ClockFit:
    """Least-squares offset+drift over (rank_t, rank0_t) release-edge
    pairs: regress y = rank0_t - rank_t on x = rank_t - t_ref. One
    pair pins offset only; zero pairs is the identity (the caller
    flags it via sync_points == 0)."""
    if not pairs:
        return ClockFit()
    t_ref = sum(t for t, _ in pairs) / len(pairs)
    xs = [t - t_ref for t, _ in pairs]
    ys = [t0 - t for t, t0 in pairs]
    my = sum(ys) / len(ys)
    var = sum(x * x for x in xs)
    drift = (sum(x * (y - my) for x, y in zip(xs, ys)) / var
             if var > 1e-9 else 0.0)
    fit = ClockFit(offset=my, drift=drift, t_ref=t_ref,
                   sync_points=len(pairs))
    res = [y - (fit.offset + fit.drift * x) for x, y in zip(xs, ys)]
    fit.residual_rms = (sum(r * r for r in res) / len(res)) ** 0.5
    return fit


def align_clocks(ranks: Dict[int, List[Dict[str, Any]]]
                 ) -> Dict[int, ClockFit]:
    """Per-rank clock fits against rank 0 (or the lowest rank present)
    from the matched barrier release edges."""
    pids = sorted(ranks)
    edges = {pid: _barrier_edges(ranks[pid]) for pid in pids}
    ref = pids[0]
    fits = {ref: ClockFit(t_ref=0.0, sync_points=sum(
        len(v) for v in edges[ref].values()))}
    for pid in pids[1:]:
        pairs: List[Tuple[float, float]] = []
        for name, mine in edges[pid].items():
            ref_edges = edges[ref].get(name) or []
            for k in range(min(len(mine), len(ref_edges))):
                pairs.append((mine[k][1], ref_edges[k][1]))
        fits[pid] = _fit(pairs)
    return fits


def _phase_totals(events: Sequence[Dict[str, Any]]
                  ) -> Tuple[Dict[str, float], float, float]:
    """One rank's summed span durations by phase, plus the first span
    start and last span end (its OWN clock)."""
    totals: Dict[str, float] = {}
    t_first: Optional[float] = None
    t_last: Optional[float] = None
    for rec in events:
        if rec.get("event") != "span":
            continue
        name = rec.get("name")
        # fmlint: disable=R001 -- parsed JSON event fields
        ts = float(rec.get("ts", rec.get("t", 0.0)))
        # fmlint: disable=R001 -- parsed JSON event fields
        dur = float(rec.get("dur", 0.0))
        phase = PHASE_SPANS.get(name)
        if phase is not None:
            totals[phase] = totals.get(phase, 0.0) + dur
        if name in PHASE_SPANS or name in BARRIER_SPANS:
            t_first = ts if t_first is None else min(t_first, ts)
            t_last = (ts + dur if t_last is None
                      else max(t_last, ts + dur))
    return totals, t_first or 0.0, t_last or 0.0


def _rank_examples(events: Sequence[Dict[str, Any]]) -> float:
    """The rank's cumulative example count from its LAST metrics
    event (counters are cumulative, so last wins)."""
    ex = 0.0
    for rec in events:
        if rec.get("event") != "metrics":
            continue
        c = rec.get("counters") or {}
        # fmlint: disable=R001 -- parsed JSON event field
        ex = float(c.get("train/examples",
                         c.get("predict/examples", 0.0)) or 0.0)
    return ex


def build_report(ranks: Dict[int, List[Dict[str, Any]]],
                 baseline_eps: Optional[float] = None
                 ) -> Dict[str, Any]:
    """The full anatomy report for per-rank event lists (the testable
    core; ``report(paths)`` is the file-reading wrapper).

    ``baseline_eps`` — a single-process examples/sec rate (e.g. the
    1-worker leg of ``bench.py --multihost``) — unlocks the absolute
    per-worker efficiency: useful compute time (examples /
    baseline_eps) over wall. Host spans alone cannot see stalls
    INSIDE the dispatched step program (the gradient allreduce runs
    in-program on multi-host), so without a baseline the report's
    ``efficiency`` is coordination efficiency — the host-visible
    barrier waits only."""
    if not ranks:
        return {"error": "no events — pass the chief metrics file "
                         "plus its .p<i> shards from a trace_spans "
                         "run"}
    fits = align_clocks(ranks)
    pids = sorted(ranks)
    edges = {pid: _barrier_edges(ranks[pid]) for pid in pids}

    # Split every matched barrier into straggler wait vs transport on
    # the aligned clock.
    straggler = {pid: 0.0 for pid in pids}
    transport = {pid: 0.0 for pid in pids}
    last_arrivals = {pid: 0 for pid in pids}
    per_barrier_wait: Dict[str, float] = {}
    names = set()
    for pid in pids:
        names.update(edges[pid])
    matched = 0
    for name in sorted(names):
        n = min(len(edges[pid].get(name) or []) for pid in pids)
        for k in range(n):
            arr = {pid: fits[pid].aligned(edges[pid][name][k][0])
                   for pid in pids}
            rel = {pid: fits[pid].aligned(edges[pid][name][k][1])
                   for pid in pids}
            last = max(arr.values())
            last_pid = max(pids, key=lambda p: arr[p])
            last_arrivals[last_pid] += 1
            matched += 1
            for pid in pids:
                s = max(0.0, last - arr[pid])
                t = max(0.0, rel[pid] - last)
                straggler[pid] += s
                transport[pid] += t
                per_barrier_wait[name] = (
                    per_barrier_wait.get(name, 0.0) + s + t)

    rank_rows: Dict[int, Dict[str, Any]] = {}
    for pid in pids:
        totals, t0, t1 = _phase_totals(ranks[pid])
        wall = max(1e-12, fits[pid].aligned(t1) - fits[pid].aligned(t0))
        phases = dict(totals)
        phases["straggler wait"] = straggler[pid]
        phases["transport"] = transport[pid]
        accounted = sum(phases.values())
        # Spans nest / overlap (train/h2d rides inside the step wall,
        # the lockstep fetch overlaps the next window's dispatch): the
        # fraction of accounted time beyond wall is the overlap the
        # protocol already wins.
        overlap = max(0.0, (accounted - wall) / accounted
                      if accounted > 0 else 0.0)
        phases["host (input+build+other)"] = max(0.0, wall - accounted)
        coord = straggler[pid] + transport[pid]
        eff = max(0.0, 1.0 - coord / wall)
        local = {k: v for k, v in phases.items()
                 if k not in WAIT_PHASES}
        dominant_local = (max(local, key=local.get) if local else "?")
        dominant = (max(phases, key=phases.get) if phases else "?")
        examples = _rank_examples(ranks[pid])
        rank_rows[pid] = {
            "wall_seconds": wall,
            "phases": phases,
            "efficiency": eff,
            "overlap_fraction": overlap,
            "last_arrivals": last_arrivals[pid],
            "dominant_phase": dominant,
            "dominant_local_phase": dominant_local,
            "examples": examples,
        }
        if baseline_eps:
            # Absolute per-worker efficiency: the time a lone worker
            # at the baseline rate would need for this rank's
            # examples, over the wall it actually took. The gap to
            # the coordination efficiency above is the stall INSIDE
            # the dispatched program.
            rank_rows[pid]["efficiency_vs_single"] = max(
                0.0, (examples / baseline_eps) / wall)

    # The straggler: the rank the others wait for most often. Its
    # dominant LOCAL phase is the why (its waits are a symptom).
    straggler_pid = max(pids, key=lambda p: last_arrivals[p])
    wall_mean = (sum(r["wall_seconds"] for r in rank_rows.values())
                 / len(rank_rows))
    s_tot = sum(straggler.values())
    t_tot = sum(transport.values())
    wall_tot = sum(r["wall_seconds"] for r in rank_rows.values())
    s_frac = s_tot / wall_tot if wall_tot else 0.0
    t_frac = t_tot / wall_tot if wall_tot else 0.0
    top_barrier = (max(per_barrier_wait, key=per_barrier_wait.get)
                   if per_barrier_wait else None)
    bar_label = (top_barrier or "collective").split("/")[-1]
    if top_barrier and s_frac >= t_frac and s_frac > 0.15:
        verdict = (
            f"{bar_label} straggler-wait {s_frac:.0%} of step; rank "
            f"{straggler_pid} "
            f"{rank_rows[straggler_pid]['dominant_local_phase']} is "
            f"the straggler")
    elif top_barrier and t_frac > 0.15:
        verdict = (
            f"{bar_label} transport {t_frac:.0%} of step (ranks "
            "arrive together; the wall is the collective itself — on "
            "CPU/gloo this also absorbs the previous step's queued "
            "device program)")
    else:
        dom = max(rank_rows[straggler_pid]["phases"],
                  key=rank_rows[straggler_pid]["phases"].get)
        frac = (rank_rows[straggler_pid]["phases"][dom]
                / rank_rows[straggler_pid]["wall_seconds"])
        if dom == "step dispatch" and len(pids) > 1:
            # The dominant time is inside the dispatched XLA program,
            # where the gradient allreduce runs on multi-host — host
            # spans cannot split that stall from compute. A baseline
            # rate (--baseline-eps / bench --multihost) quantifies it.
            verdict = (
                f"step dispatch {frac:.0%} of step — the wall is "
                "inside the dispatched program (in-program gradient "
                "allreduce + compute; host-visible barrier waits are "
                f"only {s_frac + t_frac:.0%})")
        else:
            verdict = (f"{dom} {frac:.0%} of step; no dominant "
                       "collective wait")
    eff_all = (sum(r["efficiency"] for r in rank_rows.values())
               / len(rank_rows))
    eff_single = None
    if baseline_eps and rank_rows:
        eff_single = (sum(r["efficiency_vs_single"]
                          for r in rank_rows.values())
                      / len(rank_rows))
        verdict += (f"; vs single-process rate, per-worker "
                    f"efficiency {eff_single:.2f}")
    return {
        "ranks": {pid: rank_rows[pid] for pid in pids},
        "clock": {pid: {
            "offset_ms": fits[pid].offset * 1e3,
            "drift_ppm": fits[pid].drift * 1e6,
            "sync_points": fits[pid].sync_points,
            "residual_ms": fits[pid].residual_rms * 1e3,
        } for pid in pids},
        "matched_barriers": matched,
        "top_barrier": top_barrier,
        "straggler_rank": straggler_pid,
        "straggler_wait_fraction": s_frac,
        "transport_fraction": t_frac,
        "efficiency": eff_all,
        "efficiency_vs_single": eff_single,
        "wall_seconds_mean": wall_mean,
        "verdict": verdict,
    }


def report(paths: Sequence[str],
           baseline_eps: Optional[float] = None) -> Dict[str, Any]:
    """File-reading entry point for ``fmtrace --anatomy``."""
    return build_report(events_by_rank(paths),
                        baseline_eps=baseline_eps)


def render(rep: Dict[str, Any]) -> str:
    """The human report, one string (the CLI prints it verbatim)."""
    if "error" in rep:
        return rep["error"]
    lines: List[str] = []
    lines.append("STEP ANATOMY  (clock-aligned critical path; "
                 f"{rep['matched_barriers']} matched barriers)")
    for pid, c in sorted(rep["clock"].items()):
        lines.append(
            f"  rank {pid} clock: offset {c['offset_ms']:+.3f} ms, "
            f"drift {c['drift_ppm']:+.1f} ppm, "
            f"{c['sync_points']} sync points, "
            f"residual {c['residual_ms']:.3f} ms rms")
    for pid, r in sorted(rep["ranks"].items()):
        vs = ("" if "efficiency_vs_single" not in r else
              f" ({r['efficiency_vs_single']:.2f} vs single)")
        lines.append(
            f"  rank {pid}: wall {r['wall_seconds']:.3f} s, "
            f"efficiency {r['efficiency']:.2f}{vs}, overlap "
            f"{r['overlap_fraction']:.0%}, last-to-arrive "
            f"{r['last_arrivals']}x")
        wall = r["wall_seconds"]
        for phase, v in sorted(r["phases"].items(),
                               key=lambda kv: -kv[1]):
            if v <= 0:
                continue
            lines.append(
                f"    {phase:<28s} {v:9.3f} s  {v / wall:6.1%}")
    lines.append(
        f"  straggler: rank {rep['straggler_rank']} "
        f"(straggler-wait {rep['straggler_wait_fraction']:.0%}, "
        f"transport {rep['transport_fraction']:.0%} of step)")
    lines.append(f"  verdict: {rep['verdict']}")
    return "\n".join(lines)
