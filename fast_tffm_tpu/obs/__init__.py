"""obs/ — unified run telemetry (ISSUE 2).

A dependency-free metrics registry (counters, gauges, fixed-bucket
histograms), a buffered JSONL sink that follows the same link-safety
discipline as ``utils/summaries.ScalarSummaries`` (device scalars are
buffered and bulk-fetched only at epoch/flush barriers, never per
step), and the per-run wiring that lets every stage — data pipeline,
train loop, predict sweep, lockstep sharded path — feed one merged
event stream without threading a telemetry handle through every
signature.

Off by default: everything here is a no-op until a driver activates a
``RunTelemetry`` (``metrics_file`` config knob). ``active()`` is the
one lookup instrumented code paths make; when no run is active it
returns None and the instrumented site costs one global read.

Summarize or tail the resulting file with ``python -m tools.fmstat``.
"""

from fast_tffm_tpu.obs.registry import (Counter, Gauge, Histogram,
                                        MetricsRegistry)
from fast_tffm_tpu.obs.sink import JsonlSink, read_events
from fast_tffm_tpu.obs.telemetry import (RunTelemetry, activate, active,
                                         make_telemetry, run_meta)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "JsonlSink", "read_events",
    "RunTelemetry", "activate", "active", "make_telemetry", "run_meta",
]
