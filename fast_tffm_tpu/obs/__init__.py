"""obs/ — unified run telemetry (ISSUE 2) + timeline/health (ISSUE 3).

A dependency-free metrics registry (counters, gauges, fixed-bucket
histograms), a buffered JSONL sink that follows the same link-safety
discipline as ``utils/summaries.ScalarSummaries`` (device scalars are
buffered and bulk-fetched only at epoch/flush barriers, never per
step), and the per-run wiring that lets every stage — data pipeline,
train loop, predict sweep, lockstep sharded path — feed one merged
event stream without threading a telemetry handle through every
signature.

On top of the aggregates, the timeline/health layer: ``trace.span``
brackets one stage into the same stream (export to Perfetto with
``tools/fmtrace``); ``health.Watchdog`` detects stalled runs via a
per-step heartbeat, dumps all-thread stacks, and flags non-finite loss
at the barrier fetch; driver crashes write a final forensic event with
the traceback and the sink's recent-event ring.

Off by default: everything here is a no-op until a driver activates a
``RunTelemetry`` (``metrics_file`` config knob; ``trace_spans`` and
``watchdog_stall_seconds`` gate the timeline/health layer). ``active()``
is the one lookup instrumented code paths make; when no run is active
it returns None and the instrumented site costs one global read.

Summarize or tail the resulting file with ``python -m tools.fmstat``.
"""

from fast_tffm_tpu.obs.health import Watchdog
from fast_tffm_tpu.obs.registry import (Counter, Gauge, Histogram,
                                        MetricsRegistry)
from fast_tffm_tpu.obs.sink import JsonlSink, read_events
from fast_tffm_tpu.obs.telemetry import (RunTelemetry, activate, active,
                                         make_telemetry, run_meta)
from fast_tffm_tpu.obs.trace import span

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "JsonlSink", "read_events",
    "RunTelemetry", "activate", "active", "make_telemetry", "run_meta",
    "Watchdog", "span",
]
