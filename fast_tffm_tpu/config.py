"""INI config surface compatible with the reference's ``sample.cfg``.

The reference reads a single INI file with ``[General]``/``[Train]``/
``[Predict]`` (and optionally ``[Cluster]``) sections via stdlib
ConfigParser (SURVEY.md §2 "Config system", Appendix A). This module
accepts that schema verbatim and parses it into one frozen dataclass; keys
the reference does not have (``model_type``, ``order``, ``field_num``,
bucketing knobs) extend the schema without breaking existing configs.
"""

from __future__ import annotations

import configparser
import dataclasses
import os
from typing import Tuple


def _split_ints(raw: str) -> Tuple[int, ...]:
    """Comma/whitespace-separated int list (bucket_ladder)."""
    return tuple(int(x) for x in raw.replace(",", " ").split())


def _split_files(raw: str) -> Tuple[str, ...]:
    """Comma/whitespace-separated file list (globs allowed) -> tuple."""
    out = []
    for part in raw.replace(",", " ").split():
        if part:
            out.append(part)
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class FmConfig:
    # --- [General] ---------------------------------------------------------
    vocabulary_size: int = 1 << 20
    # Reference: table is split into `vocabulary_block_num` blocks round-
    # robined across parameter servers (SURVEY §2 "Model parameters"). Here
    # the analogue is the number of row shards of the mesh table; kept for
    # config compatibility, the mesh decides actual sharding.
    vocabulary_block_num: int = 1
    hash_feature_id: bool = False
    factor_num: int = 8
    model_file: str = "./model/fm_model"
    log_file: str = ""
    # Extensions beyond upstream (BASELINE.json configs #3/#4):
    model_type: str = "fm"          # "fm" | "ffm"
    order: int = 2                  # >= 2; order>2 uses the ANOVA kernel
    field_num: int = 0              # > 0 required for model_type == "ffm"
    # Embedding-lookup backend (BASELINE config #5; lookup.py):
    # "device" keeps table+accumulator as (mesh-shardable) jax arrays with
    # gather/update fused into the train-step jit; "host" stores them in
    # host RAM (tables too big for device memory) and ships only the
    # batch's [U, D] gathered rows / row gradients across the boundary.
    lookup: str = "device"          # "device" | "host"

    # --- [Train] -----------------------------------------------------------
    train_files: Tuple[str, ...] = ()
    weight_files: Tuple[str, ...] = ()
    validation_files: Tuple[str, ...] = ()
    # Weight sidecars for validation_files (parallel lists, same format
    # as weight_files). Without this a weighted job trains weighted but
    # validates unweighted — loss and AUC would disagree about what an
    # example is worth. Extension knob (the reference has no AUC at all).
    validation_weight_files: Tuple[str, ...] = ()
    epoch_num: int = 1
    batch_size: int = 1024
    learning_rate: float = 0.01
    factor_lambda: float = 0.0
    bias_lambda: float = 0.0
    init_value_range: float = 0.01
    loss_type: str = "logistic"     # "logistic" | "mse"
    queue_size: int = 10000
    # Reference knob (reader/shuffle thread count). Parsing here is one
    # GIL-releasing C++ pass, so the honest analogue is input-pipeline
    # LOOKAHEAD: this many batches are prepared ahead of the device
    # (prefetch_depth clamps it to [2, 8]).
    shuffle_threads: int = 1
    # Parallel host data plane (README "Data plane"): batch-build
    # workers fanning the parse->hash->dedup->pack stage across host
    # cores behind a bounded ORDERED ring — the emitted batch stream is
    # bit-identical to host_threads = 1 for the same config/seed, so
    # this is a pure throughput knob. 0 = auto (min(4, host cores));
    # 1 = the serial pipeline (pre-parallel behavior). Resolved by
    # data/pipeline.resolve_host_threads; distinct from the C++
    # builder's internal feed parse threads (bench reports both).
    host_threads: int = 0
    shuffle: bool = True
    seed: int = 0
    adagrad_init: float = 0.1       # TF Adagrad accumulator init default
    save_steps: int = 0             # 0 = save only at end
    log_steps: int = 100
    # Reference knob (SURVEY Appendix A [L]): summary-writer cadence.
    # > 0 writes TensorBoard scalars (train loss, examples/sec,
    # validation AUC) every this many steps to <model_file>.tb/
    # (utils/summaries.py; buffered and flushed at epoch barriers —
    # no mid-stream device fetches up to the 1024-entry safety cap,
    # one bulk fetch per cap hit beyond it). 0 = off.
    save_summaries_steps: int = 0
    # Cap per-epoch validation at this many batches PER INPUT SHARD
    # (process) — 0 = full sweep. At Criteo-1TB scale an every-epoch
    # full validation pass costs a complete extra data sweep. The unit
    # is per-shard in every topology (a P-process job samples up to
    # P x this many batches, one cap per worker's shard).
    validation_max_batches: int = 0
    # Static-shape bucketing (TPU-specific; SURVEY §7 hard part #1):
    max_features_per_example: int = 256   # hard cap on nnz/example (truncate)
    bucket_ladder: Tuple[int, ...] = (8, 16, 32, 64, 128, 256)
    # Fixed unique-row count per batch in multi-process (fixed-shape)
    # training. 0 = auto: measured from the data at startup
    # (data/pipeline.probe_uniq_bucket). Overfull batches spill safely.
    uniq_bucket: int = 0
    # "auto" = the measured regime matrix (ops/kernel_choice.py,
    # BASELINE.md "Kernel-choice matrix"): the fused Pallas kernel
    # exactly where it measured faster (2nd-order FM on TPU, device
    # dedup, bucket width >= 64), XLA everywhere else — resolved per
    # bucket at trace time. Explicit values always win; re-measure on
    # new hardware with tools/kernel_probe.py.
    kernel: str = "auto"            # "auto" | "xla" | "pallas"
    # Where the per-batch unique-id pass runs. "host": the pipeline
    # dedups and ships (uniq_ids, local_idx) — required by mesh,
    # multi-process, and offload paths. "device": the pipeline ships raw
    # ids and the jitted step runs jnp.unique on the chip — ~40% less
    # host->device traffic per step for ~3 us of TPU sort (single-device
    # jit only). "auto" picks device where it applies. Resolved in
    # ModelSpec.from_config.
    dedup: str = "auto"             # "auto" | "host" | "device"
    # Wire format (README "Wire format"; fast_tffm_tpu/wire.py): how a
    # built batch crosses the host->device boundary. "padded" (default)
    # ships the fixed-shape [B, L] rectangles exactly as today —
    # bit-identical to every prior release. "packed" ships the CSR
    # substance instead — flat values + per-example lengths (+ the
    # dedup'd uniq table) bucketed to a power-of-two flat ladder — and
    # the jitted step/score programs rebuild the padded rectangles
    # on-device (models/fm.unpack seam), cutting per-step H2D bytes by
    # the batch's padding-waste fraction. Single-device jit paths only
    # (mesh / multi-process lockstep / offload TRAIN assemble padded
    # global arrays and resolve back to padded with a warning —
    # wire.resolve_wire is the one resolution point).
    wire_format: str = "padded"     # "padded" | "packed"
    # Wire dtypes (requires wire_format = packed): "wide" keeps f32
    # values/weights on the wire — bit-identical math. "narrow" ships
    # values and weights as float16 (ids are int32 end-to-end already)
    # and upcasts to f32 on device before any model math — about half
    # the value bytes for one rounding step on the inputs (training
    # tolerances, not bit-parity; labels stay f32).
    wire_dtypes: str = "wide"       # "wide" | "narrow"
    # Profiling (SURVEY §5 "Tracing": reference has none; we dump a
    # TensorBoard/Perfetto trace of a steady-state step window on demand):
    profile_dir: str = ""           # empty = profiling off
    profile_start_step: int = 5     # skip compile/warmup steps
    profile_num_steps: int = 10
    # Run telemetry (obs/; README "Observability"). Off by default.
    # metrics_file: JSONL event stream path; "auto" means
    # <model_file>.metrics.jsonl; multi-process runs write
    # <metrics_file>.p<i> per non-chief worker (merged at read time by
    # tools/fmstat). metrics_flush_steps: host-event flush cadence in
    # steps (device scalars still wait for epoch barriers — a flush
    # adds file I/O only, never a device fetch); 0 = epoch-only.
    metrics_file: str = ""
    metrics_flush_steps: int = 100
    # Span timeline tracing (obs/trace.py; needs metrics_file). Off by
    # default: spans are host-only events at per-batch/per-step cadence
    # — cheap, but a months-long run doesn't want them unrequested.
    # Export the stream with tools/fmtrace for ui.perfetto.dev.
    trace_spans: bool = False
    # Collective-protocol tracing (parallel/liveness.py; needs
    # metrics_file). Every guarded collective emits a `collective`
    # event (sequence number + label); `fmtrace --collectives` diffs
    # the per-rank streams — the runtime oracle for fmlint R014. Env
    # fallback: FM_PROTOCOL_TRACE=1.
    protocol_trace: bool = False
    # Step-anatomy join keys (obs/anatomy.py; README "Step anatomy").
    # On (default), the lockstep/step producers stamp window/step ids
    # and host-side phase counters into the telemetry stream — near-zero
    # cost (ids ride spans that trace_spans already gates; the phase
    # counters are host perf_counter pairs, no device fetch) — and the
    # chief emits pre-aggregated anatomy/* gauges at barrier flushes so
    # `fmstat` can render the EFFICIENCY section from the JSONL alone.
    # `fmtrace --anatomy` needs a trace_spans = true run for the full
    # clock-aligned critical-path report. Off: no ids, no anatomy/*.
    anatomy: bool = True
    # Run-health watchdog (obs/health.py; needs metrics_file). > 0:
    # a daemon thread emits a `health: stalled` event and dumps
    # all-thread stacks to <metrics_file>.stacks when no train/predict
    # step lands for this many seconds. 0 (default) = off.
    watchdog_stall_seconds: float = 0.0
    # HBM pressure threshold (obs/memory.py; README "Memory
    # observability"; needs metrics_file). > 0: a metrics flush whose
    # ledger live bytes cross this fraction of the device capacity
    # emits one `health: hbm_pressure` event per episode (re-armed
    # when live drops back below) — the early-warning signal before a
    # RESOURCE_EXHAUSTED. Inert when the backend reports no capacity
    # (CPU container). 0 (default) = off.
    mem_pressure_fraction: float = 0.0
    # Data-plane fault tolerance (README "Fault tolerance").
    # What a malformed input line does to the run (data/badlines.py):
    # "error" (default) aborts on the first bad line — the historical
    # behavior; "skip" drops the line, counts it (pipeline/bad_lines)
    # and emits rate-limited `health: bad_input` events; "quarantine"
    # additionally appends the raw line + file/lineno to
    # <metrics_file>.quarantine (<model_file>.quarantine when metrics
    # are off).
    bad_line_policy: str = "error"  # "error" | "skip" | "quarantine"
    # Circuit breaker for skip/quarantine: once bad lines exceed this
    # fraction of scanned lines (and a small absolute floor, so one
    # early bad line can't trip a tiny sample), the run aborts naming
    # the worst file — silent corpus rot must not train a garbage
    # model.
    max_bad_fraction: float = 0.01
    # Transient-IO retry (utils/retry.py): extra attempts after the
    # first for retryable errors (OSError/TimeoutError minus the
    # definitely-fatal missing-path family) on pipeline file
    # opens/reads, weight-sidecar reads, and checkpoint save/restore.
    # Backoff is io_backoff_seconds * 2^k with seeded jitter; retries
    # count io/retries in the metrics stream. 0 = fail fast.
    io_retries: int = 2
    io_backoff_seconds: float = 0.1
    # Checkpoint integrity verification before restore (checkpoint.py;
    # README "Checkpoint integrity & fallback"): "size" (default)
    # checks per-file byte counts against the save-time
    # manifest-<step>.json (catches torn/truncated writes for one stat
    # per file), "full" additionally re-hashes every byte (crc32;
    # catches silent bit rot at the cost of reading the whole
    # checkpoint once), "off" skips verification. A step that fails —
    # or raises during restore — is quarantined (renamed
    # corrupt-<step>, never deleted) and restore falls back to the
    # newest older intact step. Inspect with: python -m tools.fmckpt
    ckpt_verify: str = "size"       # "off" | "size" | "full"
    # Streaming / online learning (README "Streaming / online
    # learning"; data/stream.py + train.py). run_mode = epochs keeps
    # the historical fixed-schedule behavior; run_mode = stream follows
    # ``stream_dir`` (a directory, or a glob pattern) for arriving
    # libsvm shards and trains ONE continuous arrival-ordered pass
    # that survives indefinitely: new files are picked up every
    # ``stream_poll_seconds``, growing files are tailed with the torn
    # trailing line held back until more bytes arrive or the file is
    # sealed, and the durable stream position (per-file byte/line
    # watermark) rides every checkpoint so a restart resumes with no
    # example duplicated or skipped. ``epoch_num``/``shuffle`` have no
    # effect in stream mode (an online pass is arrival-ordered by
    # design); a ``STOP`` marker file in the stream directory ends the
    # run once every sealed byte is consumed.
    run_mode: str = "epochs"        # "epochs" | "stream"
    stream_dir: str = ""            # directory or glob of arriving shards
    stream_poll_seconds: float = 2.0
    # When an arriving file counts as SEALED (complete, safe to consume
    # through EOF): "done" requires a ``<file>.done`` marker; "quiet"
    # seals after the file's mtime has been quiet for
    # 3 x stream_poll_seconds; "auto" (default) accepts either signal.
    seal_policy: str = "auto"       # "auto" | "done" | "quiet"
    # Stream-mode checkpoint publishing: every this many seconds, save,
    # settle the integrity manifest, verify the step, and atomically
    # repoint the ``published`` pointer file in <model_file>.ckpt/ that
    # a serving process can watch (fmckpt ls shows it). 0 = no
    # publishing (periodic save_steps saves still apply).
    publish_interval_seconds: float = 0.0
    # Per-publish quality gate (README "SLOs & quality gate";
    # obs/quality.py). With ``validation_files`` set on a stream run,
    # every publish settle runs a validation sweep (AUC + loss +
    # calibration ride the same score fetches — zero extra device
    # traffic) and these thresholds decide whether the ``published``
    # pointer may move: a regressed model NEVER reaches serving — the
    # pointer stays on the last passing step, a ``health: gate_held``
    # event fires, and fmstat's verdict reads GATE-HELD.
    # publish_min_auc: absolute floor — hold the publish when the
    # sweep's AUC is below this (also the only check on the very first
    # publish, when no prior published AUC exists). 0 = off.
    publish_min_auc: float = 0.0
    # publish_max_auc_drop: relative guard — hold when AUC fell more
    # than this below the AUC of the last SUCCESSFUL publish. 0 = off.
    publish_max_auc_drop: float = 0.0
    # Whether the per-publish validation sweep runs at all. "auto"
    # (default) enables it exactly when the run declared a quality
    # objective — a gate knob above, or slo_min_auc — so a pre-existing
    # stream config with validation_files pays NO new per-publish cost
    # until it opts into quality observability; "on" forces the sweep
    # (gauges without a gate); "off" disables it (rejected when a gate
    # is configured — the gate's decision IS the sweep).
    publish_quality_eval: str = "auto"  # "auto" | "on" | "off"

    # --- [SLO] -------------------------------------------------------------
    # Declarative service-level objectives (README "SLOs & quality
    # gate"; obs/slo.py). Each knob declares one objective over the
    # metrics stream; 0 (the default) leaves that objective unset. The
    # configured spec is stamped into the run's metrics as ``slo/*``
    # gauges, so ``python -m tools.fmstat slo <metrics.jsonl>`` renders
    # the per-objective PASS/FAIL table from the JSONL alone — the one
    # operator answer to "is this deployment healthy".
    # Freshness: the last published checkpoint must be at most this
    # many seconds old at the final metrics flush.
    slo_publish_staleness_seconds: float = 0.0
    # Latency: the serving request-latency p99 must be at most this.
    slo_p99_ms: float = 0.0
    # Quality: the latest quality/validation AUC must be at least this.
    slo_min_auc: float = 0.0
    # Input health: bad lines / scanned lines must be at most this.
    slo_max_bad_fraction: float = 0.0

    # --- [Vocab] -----------------------------------------------------------
    # Unbounded-vocabulary admission (README "Unbounded vocabulary";
    # fast_tffm_tpu/vocab/). "fixed" (default) is the historical
    # behavior — feature ids mod straight into the vocabulary_size
    # table, bit-identical to every prior release. "admit" hashes ids
    # into a large fixed space (2^30) and admits only ids whose
    # sketched frequency crossed vocab_admit_threshold into private
    # table rows; everything else shares one cold row (row 0), so the
    # device table stays exactly vocabulary_size rows and batch shapes
    # never move however many distinct ids the stream carries.
    # Single-process only (the slot map is host state).
    vocab_mode: str = "fixed"       # "fixed" | "admit"
    # Sketched-frequency floor for admission AND eviction: an id is
    # admitted once its count-min estimate reaches this (unit: batches
    # the id appeared in), and a live row is evicted at a barrier once
    # its decayed estimate falls below it.
    vocab_admit_threshold: float = 2.0
    # Per-barrier decay factor on every sketch counter (epoch
    # boundary / publish settle): recency-weights the frequency so a
    # formerly-hot id ages out instead of squatting its row forever.
    # 1.0 = no decay (admission is then pure lifetime frequency).
    vocab_decay: float = 0.5
    # Count-min sketch budget in MB of float32 counters (4 hash rows).
    # Bigger = fewer collisions = less over-admission; ~1 MB covers a
    # ~10^5-id working set comfortably.
    vocab_sketch_mb: float = 1.0

    # --- [Predict] ---------------------------------------------------------
    predict_files: Tuple[str, ...] = ()
    score_path: str = "./score"

    # --- [Serve] -----------------------------------------------------------
    # Online serving (README "Serving"; fast_tffm_tpu/serve/): a
    # long-lived scorer process that loads the ``published`` checkpoint
    # step, micro-batches concurrent requests under a latency budget,
    # and hot-reloads when the pointer moves. ``run_tffm.py serve``.
    # Bind address for the stdlib HTTP front end. The default is
    # loopback-only (safe out of the box); a real deployment — one
    # server per host behind a load balancer — sets 0.0.0.0 (or the
    # host's LB-facing interface) so off-host health checks and
    # traffic can reach it.
    serve_host: str = "127.0.0.1"
    # TCP port for the stdlib HTTP front end (POST /score, GET
    # /healthz). 0 = pick an ephemeral port (logged at startup).
    serve_port: int = 7070
    # Admission-queue flush cap: a micro-batch flushes as soon as this
    # many examples are queued (or the wait budget expires). Also sizes
    # the pre-compiled batch-width ladder (powers of two up to this),
    # and bounds a single request's example count.
    serve_max_batch: int = 256
    # How long the first request in an admission window waits for
    # company before the micro-batch flushes anyway — the knob that
    # trades p50 latency for batching efficiency. 0 = flush immediately
    # (every request scores alone).
    serve_max_wait_ms: float = 5.0
    # Hot-reload poll cadence: how often the server re-reads the
    # ``published`` pointer file looking for a newly published step.
    serve_poll_seconds: float = 2.0
    # Seeded per-replica jitter on the reload poll, as a fraction of
    # serve_poll_seconds: each tick waits poll * (1 ± U(0, jitter)),
    # seeded by the replica's port, so N replicas never stat the
    # shared pointer file in lockstep (thundering herd on a network
    # filesystem). 0 = fixed cadence.
    serve_poll_jitter: float = 0.2
    # --- serving fleet (README "Serving fleet"; serve/fleet.py) ------
    # Replica count for ``run_tffm.py serve --replicas N`` (the CLI
    # flag overrides this knob). Replica i binds serve_port + i, the
    # failover proxy binds serve_proxy_port. 1 = the single-process
    # scorer, no supervisor or proxy.
    serve_replicas: int = 1
    # TCP port for the fleet's reverse proxy (the client-facing front
    # door: POST /score with retry/failover, GET /healthz aggregated
    # over the fleet). 0 = ephemeral (logged at startup).
    serve_proxy_port: int = 7080
    # How many times the proxy re-sends an idempotent POST /score to a
    # DIFFERENT ready replica after a connection-refused / timeout /
    # 5xx, before the client sees a 503. 0 = no retries.
    serve_retry_budget: int = 1
    # Session-affinity header: requests carrying this header hash
    # (rendezvous) onto one replica, so a user's burst coalesces into
    # one micro-batch flush instead of spraying the fleet. Empty
    # string disables affinity routing.
    serve_affinity_header: str = "X-FM-Affinity"
    # Fraction of proxy traffic directed at the canary replica (the
    # last replica, serving the ``published-canary`` pointer) when a
    # canary step is published. 0 = no canary traffic split.
    serve_canary_fraction: float = 0.0
    # Shadow mode: duplicate sampled traffic to the canary replica in
    # the background, score and COMPARE (proxy/canary_score_delta
    # gauge) but never return canary scores to clients. Implies the
    # canary replica receives no primary traffic.
    serve_canary_shadow: bool = False
    # Supervisor restart backoff base: a dead replica restarts after
    # this many seconds, doubling per consecutive failure (capped at
    # 16x), reset once the replica reports healthy again.
    serve_restart_backoff_seconds: float = 1.0
    # Who drives hot reloads: "poll" (default) — the in-process
    # watcher reloads when the pointer moves; "external" — the
    # watcher only records the pointer (gauges stay fresh) and an
    # external coordinator (the fleet supervisor's staggered-reload
    # protocol) triggers reloads via POST /reload.
    serve_reload_mode: str = "poll"
    # Which pointer file this scorer follows: "published" (default)
    # or "canary" (the ``published-canary`` pointer, falling back to
    # ``published`` until a canary step exists). The fleet supervisor
    # sets "canary" on the canary replica.
    serve_pointer: str = "published"
    # Bound on concurrently in-flight proxied /score requests: beyond
    # it the proxy sheds with 503 + Retry-After instead of wedging an
    # unbounded pile of connection threads.
    serve_proxy_max_inflight: int = 64
    # Supervisor health-poll cadence: how often each replica's
    # /healthz is read for the alive/ready split (restart decisions
    # ride "alive", proxy routing rides "ready").
    serve_health_poll_seconds: float = 0.5

    # --- [Cluster] ---------------------------------------------------------
    # Reference: ps_hosts/worker_hosts for the TF1 PS runtime (SURVEY §3.2).
    # Here retained for CLI compatibility; mapped onto jax.distributed
    # coordinator/process env (parallel/distributed.py).
    ps_hosts: Tuple[str, ...] = ()
    worker_hosts: Tuple[str, ...] = ()
    # Cluster bring-up budget (parallel/distributed.py): total seconds
    # a worker keeps retrying to reach the jax.distributed coordinator
    # before raising (naming the coordinator address and this process).
    # Generous by default: the coordinator pod/task often boots LAST,
    # and a worker that gives up in seconds turns a routine staggered
    # start into a failed job — but a worker must never hang forever
    # on a coordinator that will never come up.
    cluster_connect_timeout_seconds: float = 300.0
    # Compute-plane fault tolerance (README "Elastic multi-host";
    # parallel/liveness.py). Deadline on every blocking host collective
    # (lockstep window allgathers, restore broadcasts, barrier syncs):
    # on expiry the liveness table is consulted, a `health: worker_lost`
    # diagnosis names the peers that stopped heartbeating, stacks are
    # dumped, and a WorkerLostError is raised instead of hanging
    # forever. 0 = no deadline (the historical hang-forever behavior).
    collective_timeout_seconds: float = 300.0
    # Heartbeat-lease renewal interval: each worker renews a lease file
    # in <model_file>.hb/ on a daemon thread (liveness = process alive,
    # not making progress); a peer is presumed lost once its lease is
    # ~4 intervals old. The lease's monitor thread is also what
    # enforces collective_timeout_seconds on a BLOCKED collective, and
    # its presence is what allows jax's own abort-all-survivors death
    # detection to be replaced. 0 disables the layer entirely: jax's
    # native detection stays on (survivors abort ~100s after a task
    # death instead of diagnosing and recovering), and the deadline
    # guard only converts collectives that RAISE. elastic = shrink
    # requires it.
    heartbeat_seconds: float = 5.0
    # What survivors do on WorkerLostError: "off" fails fast with the
    # named-worker diagnosis; "shrink" tears down the distributed
    # client, reforms the cluster from the surviving membership,
    # redistributes the lost worker's input shards, restores from the
    # last verified checkpoint, and continues. "grow" implies shrink
    # AND additionally heals the cluster back toward full capacity:
    # a replacement launched with `run_tffm.py train <cfg> --join`
    # publishes a join-request lease in <model_file>.hb/, and the
    # running cluster admits it at the next safe barrier (epoch
    # boundary in run_mode = epochs, publish settle in run_mode =
    # stream) through a generation-bumped reform — the newcomer comes
    # up through the full durable-state path (verified restore,
    # chief-broadcast watermark/vocab) and input shards re-balance
    # over the new membership.
    elastic: str = "off"            # "off" | "shrink" | "grow"
    # Elastic GROW rendezvous (elastic = grow): how long a grow reform
    # waits for every PLANNED joiner to announce + heartbeat before
    # committing membership without the missing ones — a joiner that
    # dies mid-rendezvous must never wedge the incumbents. Floored at
    # runtime by the lease staleness window so a dead joiner is
    # visibly dead before it is dropped.
    join_settle_seconds: float = 5.0
    # The joiner's (`--join`) total budget to be admitted by a running
    # cluster before giving up with an actionable error.
    # 0 = use cluster_connect_timeout_seconds.
    join_timeout_seconds: float = 0.0

    def __post_init__(self):
        if self.order < 2:
            raise ValueError(f"order must be >= 2, got {self.order}")
        if self.model_type not in ("fm", "ffm"):
            raise ValueError(f"unknown model_type {self.model_type!r}")
        if self.model_type == "ffm":
            if self.field_num <= 0:
                raise ValueError("model_type=ffm requires field_num > 0")
            if self.order != 2:
                raise ValueError("ffm supports order=2 only")
            # The field-bucketed scorer's biggest intermediate is
            # [B, F, F, k] (ops/interaction.py); warn before a config
            # quietly asks for a multi-GB tensor per step.
            ffm_bytes = (self.batch_size * self.field_num ** 2
                         * self.factor_num * 4)
            if ffm_bytes > 2 << 30:
                import warnings
                warnings.warn(
                    f"ffm intermediate [batch_size, field_num^2, "
                    f"factor_num] is {ffm_bytes / 2**30:.1f} GB per step "
                    f"(B={self.batch_size}, F={self.field_num}, "
                    f"k={self.factor_num}); reduce batch_size or "
                    "field_num to fit device memory")
        if self.loss_type not in ("logistic", "mse"):
            raise ValueError(f"unknown loss_type {self.loss_type!r}")
        if self.kernel not in ("auto", "xla", "pallas"):
            raise ValueError(f"unknown kernel {self.kernel!r}")
        if self.dedup not in ("auto", "host", "device"):
            raise ValueError(f"unknown dedup {self.dedup!r}")
        if self.dedup == "device" and self.lookup == "host":
            raise ValueError(
                "dedup = device requires lookup = device: the host-offload "
                "backend gathers rows on the host and needs the host-side "
                "unique pass")
        if self.lookup not in ("device", "host"):
            raise ValueError(f"unknown lookup {self.lookup!r}")
        if self.wire_format not in ("padded", "packed"):
            raise ValueError(f"unknown wire_format {self.wire_format!r} "
                             "(want padded | packed)")
        if self.wire_dtypes not in ("wide", "narrow"):
            raise ValueError(f"unknown wire_dtypes {self.wire_dtypes!r} "
                             "(want wide | narrow)")
        if self.wire_dtypes == "narrow" and self.wire_format != "packed":
            raise ValueError(
                "wire_dtypes = narrow requires wire_format = packed: "
                "the padded rectangles are the bit-identical legacy "
                "layout — narrowing them silently would betray the "
                "wide-default parity contract")
        if self.factor_num <= 0:
            raise ValueError("factor_num must be positive")
        if self.vocabulary_size <= 0:
            raise ValueError("vocabulary_size must be positive")
        lad = self.bucket_ladder
        if not lad or any(b <= 0 for b in lad) or list(lad) != sorted(
                set(lad)):
            raise ValueError(
                f"bucket_ladder must be a strictly increasing tuple of "
                f"positive ints, got {lad}")
        ub = self.uniq_bucket
        if ub and (ub < 64 or ub & (ub - 1)):
            raise ValueError(
                f"uniq_bucket must be 0 (auto) or a power of two >= 64 "
                f"(mesh sharding divides the unique axis), got {ub}")
        if self.validation_weight_files and not self.validation_files:
            raise ValueError(
                "validation_weight_files given without validation_files")
        # Sidecar lists must pair 1:1 with their data lists. Globs
        # expand at iteration time, so an exact config-time length check
        # is only sound when no entry is a pattern — but that's the
        # common case, and catching it here beats dying at the first
        # validation sweep hours into a run.
        for files, sidecars, name in (
                (self.train_files, self.weight_files, "weight_files"),
                (self.validation_files, self.validation_weight_files,
                 "validation_weight_files")):
            literal = not any(
                c in f for f in files + sidecars for c in "*?[")
            if (sidecars and literal and files
                    and len(sidecars) != len(files)):
                raise ValueError(
                    f"{name} must pair 1:1 with its data files "
                    f"({len(sidecars)} sidecars vs {len(files)} files)")
        if self.validation_max_batches < 0:
            raise ValueError(
                f"validation_max_batches must be >= 0 (0 = full sweep), "
                f"got {self.validation_max_batches}")
        if self.metrics_flush_steps < 0:
            raise ValueError(
                f"metrics_flush_steps must be >= 0 (0 = flush at epoch "
                f"barriers only), got {self.metrics_flush_steps}")
        if self.watchdog_stall_seconds < 0:
            raise ValueError(
                f"watchdog_stall_seconds must be >= 0 (0 = watchdog "
                f"off), got {self.watchdog_stall_seconds}")
        if not 0.0 <= self.mem_pressure_fraction <= 1.0:
            raise ValueError(
                f"mem_pressure_fraction must be in [0, 1] (0 = off), "
                f"got {self.mem_pressure_fraction}")
        if self.bad_line_policy not in ("error", "skip", "quarantine"):
            raise ValueError(
                f"unknown bad_line_policy {self.bad_line_policy!r} "
                "(want error | skip | quarantine)")
        if not 0.0 <= self.max_bad_fraction <= 1.0:
            raise ValueError(
                f"max_bad_fraction must be in [0, 1], got "
                f"{self.max_bad_fraction}")
        if self.host_threads < 0:
            raise ValueError(
                f"host_threads must be >= 0 (0 = auto, 1 = serial), "
                f"got {self.host_threads}")
        if self.io_retries < 0:
            raise ValueError(
                f"io_retries must be >= 0 (0 = fail fast), got "
                f"{self.io_retries}")
        if self.io_backoff_seconds < 0:
            raise ValueError(
                f"io_backoff_seconds must be >= 0, got "
                f"{self.io_backoff_seconds}")
        if self.ckpt_verify not in ("off", "size", "full"):
            raise ValueError(
                f"unknown ckpt_verify {self.ckpt_verify!r} "
                "(want off | size | full)")
        if self.run_mode not in ("epochs", "stream"):
            raise ValueError(
                f"unknown run_mode {self.run_mode!r} "
                "(want epochs | stream)")
        if self.seal_policy not in ("auto", "done", "quiet"):
            raise ValueError(
                f"unknown seal_policy {self.seal_policy!r} "
                "(want auto | done | quiet)")
        if self.stream_poll_seconds <= 0:
            raise ValueError(
                f"stream_poll_seconds must be > 0, got "
                f"{self.stream_poll_seconds}")
        if self.publish_interval_seconds < 0:
            raise ValueError(
                f"publish_interval_seconds must be >= 0 (0 = no "
                f"publishing), got {self.publish_interval_seconds}")
        if self.run_mode == "stream":
            if not self.stream_dir:
                raise ValueError(
                    "run_mode = stream requires stream_dir (a "
                    "directory or glob of arriving libsvm shards)")
            if self.train_files:
                raise ValueError(
                    "train_files is set but run_mode = stream consumes "
                    "stream_dir; drop train_files (or run_mode) — a "
                    "silently untrained corpus is always a config "
                    "mistake")
            if self.weight_files:
                raise ValueError(
                    "run_mode = stream does not support weight_files: "
                    "weight sidecars pair lines to a FIXED corpus, "
                    "which an append-only stream is not")
        elif self.stream_dir:
            raise ValueError(
                "stream_dir is set but run_mode is 'epochs'; set "
                "run_mode = stream (or drop stream_dir) — a silently "
                "ignored stream directory is always a config mistake")
        for knob in ("publish_min_auc", "publish_max_auc_drop"):
            v = getattr(self, knob)
            if not 0.0 <= v <= 1.0:
                raise ValueError(
                    f"{knob} must be in [0, 1] (0 = gate check off), "
                    f"got {v}")
        if self.publish_min_auc or self.publish_max_auc_drop:
            # The gate evaluates a validation sweep at publish settles;
            # without a corpus to sweep (or publishes to gate) the
            # knobs would be silently inert — always a config mistake.
            if self.run_mode != "stream":
                raise ValueError(
                    "publish_min_auc/publish_max_auc_drop gate stream-"
                    "mode publishes; set run_mode = stream (epoch-mode "
                    "runs never publish, so the gate would silently "
                    "never run)")
            if not self.validation_files:
                raise ValueError(
                    "publish_min_auc/publish_max_auc_drop need "
                    "validation_files: the gate's decision IS a "
                    "validation sweep at each publish settle")
            if self.publish_interval_seconds <= 0:
                raise ValueError(
                    "publish_min_auc/publish_max_auc_drop need "
                    "publish_interval_seconds > 0: the gate rides "
                    "publish settles, and a never-publishing stream "
                    "has nothing to gate")
        if self.publish_quality_eval not in ("auto", "on", "off"):
            raise ValueError(
                f"unknown publish_quality_eval "
                f"{self.publish_quality_eval!r} (want auto | on | off)")
        if (self.publish_quality_eval == "off"
                and (self.publish_min_auc or self.publish_max_auc_drop)):
            raise ValueError(
                "publish_quality_eval = off conflicts with the publish "
                "gate knobs: the gate's decision IS the per-publish "
                "validation sweep")
        if self.publish_quality_eval == "on":
            if self.run_mode != "stream" or not self.validation_files \
                    or self.publish_interval_seconds <= 0:
                raise ValueError(
                    "publish_quality_eval = on needs run_mode = "
                    "stream, validation_files, and "
                    "publish_interval_seconds > 0: the sweep runs at "
                    "publish settles over the validation corpus")
        if self.slo_publish_staleness_seconds < 0:
            raise ValueError(
                f"slo_publish_staleness_seconds must be >= 0 (0 = "
                f"objective unset), got "
                f"{self.slo_publish_staleness_seconds}")
        if self.slo_p99_ms < 0:
            raise ValueError(
                f"slo_p99_ms must be >= 0 (0 = objective unset), got "
                f"{self.slo_p99_ms}")
        if not 0.0 <= self.slo_min_auc <= 1.0:
            raise ValueError(
                f"slo_min_auc must be in [0, 1] (0 = objective unset), "
                f"got {self.slo_min_auc}")
        if not 0.0 <= self.slo_max_bad_fraction <= 1.0:
            raise ValueError(
                f"slo_max_bad_fraction must be in [0, 1] (0 = "
                f"objective unset), got {self.slo_max_bad_fraction}")
        if self.vocab_mode not in ("fixed", "admit"):
            raise ValueError(
                f"unknown vocab_mode {self.vocab_mode!r} "
                "(want fixed | admit)")
        if self.vocab_admit_threshold < 1:
            raise ValueError(
                f"vocab_admit_threshold must be >= 1 (a count floor), "
                f"got {self.vocab_admit_threshold}")
        if not 0.0 < self.vocab_decay <= 1.0:
            raise ValueError(
                f"vocab_decay must be in (0, 1] (1 = no decay), got "
                f"{self.vocab_decay}")
        if self.vocab_sketch_mb <= 0:
            raise ValueError(
                f"vocab_sketch_mb must be > 0, got "
                f"{self.vocab_sketch_mb}")
        if self.vocab_mode == "admit" and self.vocabulary_size < 2:
            raise ValueError(
                "vocab_mode = admit needs vocabulary_size >= 2: row 0 "
                "is the shared cold row, admitted ids get the rest")
        if (self.vocab_mode == "admit" and self.run_mode == "stream"
                and self.publish_interval_seconds <= 0):
            raise ValueError(
                "vocab_mode = admit with run_mode = stream needs "
                "publish_interval_seconds > 0: admission/eviction "
                "barriers ride publish settles, so a never-publishing "
                "stream would never admit a single id — the whole run "
                "would silently train through the shared cold row")
        if not self.serve_host:
            raise ValueError(
                "serve_host must be a bind address (127.0.0.1 for "
                "loopback-only, 0.0.0.0 for all interfaces)")
        if not 0 <= self.serve_port <= 65535:
            raise ValueError(
                f"serve_port must be in [0, 65535] (0 = ephemeral), "
                f"got {self.serve_port}")
        if self.serve_max_batch < 1:
            raise ValueError(
                f"serve_max_batch must be >= 1, got "
                f"{self.serve_max_batch}")
        if self.serve_max_wait_ms < 0:
            raise ValueError(
                f"serve_max_wait_ms must be >= 0 (0 = flush "
                f"immediately), got {self.serve_max_wait_ms}")
        if self.serve_poll_seconds <= 0:
            raise ValueError(
                f"serve_poll_seconds must be > 0, got "
                f"{self.serve_poll_seconds}")
        if not 0.0 <= self.serve_poll_jitter < 1.0:
            raise ValueError(
                f"serve_poll_jitter must be in [0, 1) (a fraction of "
                f"serve_poll_seconds), got {self.serve_poll_jitter}")
        if self.serve_replicas < 1:
            raise ValueError(
                f"serve_replicas must be >= 1, got "
                f"{self.serve_replicas}")
        if self.serve_replicas > 1 and self.serve_port == 0:
            raise ValueError(
                "serve_replicas > 1 needs an explicit serve_port: "
                "replica i binds serve_port + i, so an ephemeral base "
                "port cannot lay out the fleet")
        if not 0 <= self.serve_proxy_port <= 65535:
            raise ValueError(
                f"serve_proxy_port must be in [0, 65535] (0 = "
                f"ephemeral), got {self.serve_proxy_port}")
        if self.serve_retry_budget < 0:
            raise ValueError(
                f"serve_retry_budget must be >= 0 (0 = no retries), "
                f"got {self.serve_retry_budget}")
        if not 0.0 <= self.serve_canary_fraction <= 1.0:
            raise ValueError(
                f"serve_canary_fraction must be in [0, 1], got "
                f"{self.serve_canary_fraction}")
        if ((self.serve_canary_fraction > 0 or self.serve_canary_shadow)
                and self.serve_replicas < 2):
            raise ValueError(
                "canary scoring (serve_canary_fraction > 0 or "
                "serve_canary_shadow) needs serve_replicas >= 2: the "
                "canary is one replica of the fleet, and the rest must "
                "still carry primary traffic")
        if self.serve_restart_backoff_seconds <= 0:
            raise ValueError(
                f"serve_restart_backoff_seconds must be > 0, got "
                f"{self.serve_restart_backoff_seconds}")
        if self.serve_reload_mode not in ("poll", "external"):
            raise ValueError(
                f"unknown serve_reload_mode {self.serve_reload_mode!r} "
                "(want poll | external)")
        if self.serve_pointer not in ("published", "canary"):
            raise ValueError(
                f"unknown serve_pointer {self.serve_pointer!r} "
                "(want published | canary)")
        if self.serve_proxy_max_inflight < 1:
            raise ValueError(
                f"serve_proxy_max_inflight must be >= 1, got "
                f"{self.serve_proxy_max_inflight}")
        if self.serve_health_poll_seconds <= 0:
            raise ValueError(
                f"serve_health_poll_seconds must be > 0, got "
                f"{self.serve_health_poll_seconds}")
        if self.cluster_connect_timeout_seconds <= 0:
            raise ValueError(
                f"cluster_connect_timeout_seconds must be > 0, got "
                f"{self.cluster_connect_timeout_seconds}")
        if self.collective_timeout_seconds < 0:
            raise ValueError(
                f"collective_timeout_seconds must be >= 0 (0 = no "
                f"deadline), got {self.collective_timeout_seconds}")
        if self.heartbeat_seconds < 0:
            raise ValueError(
                f"heartbeat_seconds must be >= 0 (0 = liveness off), "
                f"got {self.heartbeat_seconds}")
        if self.elastic not in ("off", "shrink", "grow"):
            raise ValueError(
                f"unknown elastic {self.elastic!r} "
                "(want off | shrink | grow)")
        if self.elastic != "off" and not self.heartbeat_seconds:
            raise ValueError(
                f"elastic = {self.elastic} requires heartbeat_seconds "
                "> 0: membership (survivors AND joiners) is decided "
                "from the heartbeat leases in <model_file>.hb/")
        if self.join_settle_seconds <= 0:
            raise ValueError(
                f"join_settle_seconds must be > 0, got "
                f"{self.join_settle_seconds}")
        if self.join_timeout_seconds < 0:
            raise ValueError(
                f"join_timeout_seconds must be >= 0 (0 = the "
                f"cluster_connect budget), got "
                f"{self.join_timeout_seconds}")
        if (self.elastic == "grow" and self.run_mode == "stream"
                and self.publish_interval_seconds <= 0):
            raise ValueError(
                "elastic = grow with run_mode = stream requires "
                "publish_interval_seconds > 0: a streaming cluster "
                "admits joiners at publish settles (the stream's safe "
                "barriers) — a never-publishing stream would never "
                "admit a replacement worker")
        if self.weight_files and not self.train_files:
            # Mirror of the validation_weight_files check above: a
            # sidecar list with nothing to pair against is always a
            # config mistake, and catching it here beats a silent
            # no-op (or a late pipeline error) downstream.
            raise ValueError("weight_files given without train_files")
        if ub and self.max_features_per_example >= ub:
            raise ValueError(
                f"uniq_bucket ({ub}) must exceed max_features_per_example "
                f"({self.max_features_per_example}): one example alone "
                "may otherwise overflow the unique-row budget mid-run")

    @property
    def row_dim(self) -> int:
        """Per-row parameter count: k latent factors (× fields for FFM) + 1
        linear weight. Mirrors the reference's `[vocab, factor_num + 1]`
        table layout (SURVEY §2 "Model parameters")."""
        k = self.factor_num
        if self.model_type == "ffm":
            return k * self.field_num + 1
        return k + 1

    @property
    def prefetch_depth(self) -> int:
        """Input-pipeline lookahead in batches (data/pipeline.prefetch),
        mapped from the reference's ``shuffle_threads`` knob."""
        return max(2, min(self.shuffle_threads, 8))

    @property
    def pad_id(self) -> int:
        """Sentinel row index used for padding; one extra dead row is
        appended to the table so padded positions gather zeros and their
        gradients land harmlessly (and are masked out of the reg term)."""
        return self.vocabulary_size

    @property
    def num_rows(self) -> int:
        return self.vocabulary_size + 1

    @property
    def ckpt_rows(self) -> int:
        """Table rows as stored in checkpoints and on any mesh: num_rows
        rounded up to a multiple of 4096. The fixed multiple makes the
        stored shape divisible by every power-of-two device mesh (TPU
        slices are powers of two; make_mesh enforces it), so checkpoints
        restore row-sharded on ANY topology without ever assembling the
        table on one host — jax shardings require evenly divisible dims.
        The pad rows sit past pad_id: no feature id can reach them."""
        return -(-self.num_rows // 4096) * 4096


_GENERAL_KEYS = {
    "vocabulary_size": int,
    "vocabulary_block_num": int,
    "hash_feature_id": bool,
    "factor_num": int,
    "model_file": str,
    "log_file": str,
    "model_type": str,
    "order": int,
    "field_num": int,
    "lookup": str,
    "dedup": str,
}
_TRAIN_KEYS = {
    "train_files": _split_files,
    "weight_files": _split_files,
    "validation_files": _split_files,
    "validation_weight_files": _split_files,
    "epoch_num": int,
    "batch_size": int,
    "learning_rate": float,
    "factor_lambda": float,
    "bias_lambda": float,
    "init_value_range": float,
    "loss_type": str,
    "queue_size": int,
    "shuffle_threads": int,
    "host_threads": int,
    "shuffle": bool,
    "seed": int,
    "adagrad_init": float,
    "save_steps": int,
    "log_steps": int,
    "save_summaries_steps": int,
    "validation_max_batches": int,
    "max_features_per_example": int,
    "bucket_ladder": _split_ints,
    "uniq_bucket": int,
    "kernel": str,
    "dedup": str,  # accepted in [General] too (model-level knob)
    "wire_format": str,
    "wire_dtypes": str,
    "profile_dir": str,
    "profile_start_step": int,
    "profile_num_steps": int,
    "metrics_file": str,
    "metrics_flush_steps": int,
    "trace_spans": bool,
    "protocol_trace": bool,
    "anatomy": bool,
    "watchdog_stall_seconds": float,
    "mem_pressure_fraction": float,
    "bad_line_policy": str,
    "max_bad_fraction": float,
    "io_retries": int,
    "io_backoff_seconds": float,
    "ckpt_verify": str,
    "run_mode": str,
    "stream_dir": str,
    "stream_poll_seconds": float,
    "seal_policy": str,
    "publish_interval_seconds": float,
    "publish_min_auc": float,
    "publish_max_auc_drop": float,
    "publish_quality_eval": str,
}
_SLO_KEYS = {
    "slo_publish_staleness_seconds": float,
    "slo_p99_ms": float,
    "slo_min_auc": float,
    "slo_max_bad_fraction": float,
}
_VOCAB_KEYS = {
    "vocab_mode": str,
    "vocab_admit_threshold": float,
    "vocab_decay": float,
    "vocab_sketch_mb": float,
}
_PREDICT_KEYS = {
    "predict_files": _split_files,
    "score_path": str,
}
_SERVE_KEYS = {
    "serve_host": str,
    "serve_port": int,
    "serve_max_batch": int,
    "serve_max_wait_ms": float,
    "serve_poll_seconds": float,
    "serve_poll_jitter": float,
    "serve_replicas": int,
    "serve_proxy_port": int,
    "serve_retry_budget": int,
    "serve_affinity_header": str,
    "serve_canary_fraction": float,
    "serve_canary_shadow": bool,
    "serve_restart_backoff_seconds": float,
    "serve_reload_mode": str,
    "serve_pointer": str,
    "serve_proxy_max_inflight": int,
    "serve_health_poll_seconds": float,
}
_CLUSTER_KEYS = {
    "ps_hosts": _split_files,
    "worker_hosts": _split_files,
    "cluster_connect_timeout_seconds": float,
    "collective_timeout_seconds": float,
    "heartbeat_seconds": float,
    "elastic": str,
    "join_settle_seconds": float,
    "join_timeout_seconds": float,
}


def load_config(path: str) -> FmConfig:
    """Read a reference-style INI file into an FmConfig.

    Unknown keys raise, so typos in configs fail loudly (the reference's
    ConfigParser silently ignores them; failing loudly is strictly safer
    and costs no compatibility for valid configs).
    """
    cp = configparser.ConfigParser(inline_comment_prefixes=(";", "#"))
    read = cp.read(path)
    if not read:
        raise FileNotFoundError(path)

    kwargs = {}
    # The one section->keys mapping: drives both the consume loop and
    # the wrong-section hint, so the two cannot diverge.
    sections = {"General": _GENERAL_KEYS, "Train": _TRAIN_KEYS,
                "SLO": _SLO_KEYS, "Vocab": _VOCAB_KEYS,
                "Predict": _PREDICT_KEYS, "Serve": _SERVE_KEYS,
                "Cluster": _CLUSTER_KEYS}

    def consume(section: str, keys):
        if not cp.has_section(section):
            return
        for name, raw in cp.items(section):
            if name not in keys:
                # A key that exists in ANOTHER section is the common
                # miss (e.g. the lookup/kernel/dedup extension knobs
                # live in [General]); name the right home in the error.
                home = next((s for s, k in sections.items()
                             if name in k), None)
                hint = (f" (this key belongs in [{home}])"
                        if home else "")
                raise KeyError(
                    f"unknown config key [{section}] {name}{hint}")
            conv = keys[name]
            if conv is bool:
                kwargs[name] = cp.getboolean(section, name)
            else:
                kwargs[name] = conv(raw)

    for section, keys in sections.items():
        consume(section, keys)
    cfg = FmConfig(**kwargs)
    # Reference knobs accepted for config compatibility but with no effect
    # here — tell the user instead of silently ignoring a tuned value.
    import warnings
    if cfg.vocabulary_block_num > 1:
        warnings.warn(
            f"vocabulary_block_num = {cfg.vocabulary_block_num} is accepted "
            "for compatibility but has no effect: the reference used it to "
            "partition the table across parameter servers; here the device "
            "mesh decides row sharding (parallel/sharded.py)")
    return cfg


def apply_env_overrides(cfg: FmConfig) -> FmConfig:
    """Per-process one-off overrides from ``FM_<KNOB>`` env vars —
    the convention run_tffm.py applies to every CLI run, and the
    fleet supervisor uses to steer each replica child (its own
    ``serve_port``, its metrics shard, external reload mode, the
    canary pointer) without writing N config files. Every variable
    name maps to a real knob (fmlint R009 pins this), and the values
    go through dataclasses.replace, so they get the same
    ``__post_init__`` validation a config file does."""
    updates = {}
    v = os.environ.get("FM_METRICS_FILE")
    if v:
        updates["metrics_file"] = v
    v = os.environ.get("FM_TRACE_SPANS", "")
    if v.strip().lower() in ("1", "true", "yes", "on"):
        updates["trace_spans"] = True
    v = os.environ.get("FM_WATCHDOG_STALL_SECONDS")
    if v:
        updates["watchdog_stall_seconds"] = float(v)
    v = os.environ.get("FM_SERVE_PORT")
    if v:
        updates["serve_port"] = int(v)
    v = os.environ.get("FM_SERVE_RELOAD_MODE")
    if v:
        updates["serve_reload_mode"] = v
    v = os.environ.get("FM_SERVE_POINTER")
    if v:
        updates["serve_pointer"] = v
    return dataclasses.replace(cfg, **updates) if updates else cfg
