"""Training metrics: streaming AUC and throughput.

The reference logs only ``step, loss`` (SURVEY.md §5 "Metrics"); the
north-star metric adds test-AUC and examples/sec/chip (BASELINE.json), so
both are first-class here. AUC is the histogram/binned estimator (the same
approach as TF's AUC metric): O(1) memory, streaming, deterministic.
"""

from __future__ import annotations

import numpy as np


def sigmoid(x: np.ndarray) -> np.ndarray:
    out = np.empty_like(x, dtype=np.float64)
    pos = x >= 0
    out[pos] = 1.0 / (1.0 + np.exp(-x[pos]))
    ex = np.exp(x[~pos])
    out[~pos] = ex / (1.0 + ex)
    return out


class StreamingAUC:
    """Binned Mann-Whitney AUC over a monotone squash of the scores.

    update() takes raw scores (logits) and {0,1} labels; ties within a bin
    contribute 1/2 (trapezoidal), so with enough bins this converges to the
    exact rank statistic. Weights: examples with weight 0 (batch padding)
    are dropped; other weights scale their example's contribution.

    The squash is arctan-based, NOT the sigmoid: sigmoid binning
    collapses every logit past ~ln(num_bins) (~9.7 at 2^14 bins) into
    one tie bin, so a confidently-separating model reads toward 0.5
    (measured: exact AUC 0.837 -> binned 0.5 on N(40, 1) logits).
    arctan(x/4)'s tail resolution keeps logits distinguishable out to
    |x| ~ 4*num_bins/pi (~21k at the default bins) while matching
    sigmoid-class resolution near 0. NaN scores raise — binning NaN
    would otherwise surface as an unrelated IndexError.
    """

    def __init__(self, num_bins: int = 1 << 14):
        self.num_bins = num_bins
        self.pos = np.zeros(num_bins, dtype=np.float64)
        self.neg = np.zeros(num_bins, dtype=np.float64)

    def update(self, scores: np.ndarray, labels: np.ndarray,
               weights: np.ndarray | None = None) -> None:
        scores = np.asarray(scores, dtype=np.float64).ravel()
        labels = np.asarray(labels, dtype=np.float64).ravel()
        w = (np.ones_like(scores) if weights is None
             else np.asarray(weights, dtype=np.float64).ravel())
        keep = w > 0
        scores, labels, w = scores[keep], labels[keep], w[keep]
        if np.isnan(scores).any():
            raise ValueError(
                "NaN scores passed to StreamingAUC.update — the model "
                "has diverged (check learning_rate / init_value_range)")
        u = 0.5 + np.arctan(scores / 4.0) / np.pi
        bins = np.minimum((u * self.num_bins).astype(np.int64),
                          self.num_bins - 1)
        is_pos = labels >= 0.5
        np.add.at(self.pos, bins[is_pos], w[is_pos])
        np.add.at(self.neg, bins[~is_pos], w[~is_pos])

    def result(self) -> float:
        """AUC = P(score_pos > score_neg) + 0.5 P(tie)."""
        n_pos = self.pos.sum()
        n_neg = self.neg.sum()
        if n_pos == 0 or n_neg == 0:
            return float("nan")
        neg_below = np.cumsum(self.neg) - self.neg   # negatives in lower bins
        pairs = np.sum(self.pos * (neg_below + 0.5 * self.neg))
        return float(pairs / (n_pos * n_neg))

    def reset(self) -> None:
        self.pos[:] = 0.0
        self.neg[:] = 0.0


def exact_auc(scores: np.ndarray, labels: np.ndarray,
              weights: np.ndarray | None = None) -> float:
    """O(n log n) exact AUC — test oracle for StreamingAUC.

    With ``weights``, each (pos, neg) pair contributes w_pos * w_neg
    (ties half) and the result is pairs / (W_pos * W_neg) — the same
    statistic StreamingAUC converges to with weighted bin counts.
    """
    scores = np.asarray(scores, dtype=np.float64).ravel()
    labels = np.asarray(labels, dtype=np.float64).ravel() >= 0.5
    w = (np.ones_like(scores) if weights is None
         else np.asarray(weights, dtype=np.float64).ravel())
    order = np.argsort(scores, kind="mergesort")
    s, y, w = scores[order], labels[order], w[order]
    n = len(s)
    wpos = np.where(y, w, 0.0)
    wneg = np.where(y, 0.0, w)
    neg_below = np.cumsum(wneg) - wneg  # strictly-lower negative weight
    pairs = 0.0
    i = 0
    while i < n:  # tie groups share one (neg_below, group-neg) context
        j = i
        while j + 1 < n and s[j + 1] == s[i]:
            j += 1
        g_pos = wpos[i:j + 1].sum()
        g_neg = wneg[i:j + 1].sum()
        pairs += g_pos * (neg_below[i] + 0.5 * g_neg)
        i = j + 1
    W_pos, W_neg = wpos.sum(), wneg.sum()
    if W_pos == 0 or W_neg == 0:
        return float("nan")
    return float(pairs / (W_pos * W_neg))
