"""Chunked device->host fetches for scoring sweeps.

A PER-BATCH fetch syncs the dispatch pipeline every step — ruinous over
a proxied device link (BASELINE.md "Device-link sync pathology") —
while holding an unbounded sweep's scores grows device memory linearly.
``ChunkedFetcher`` is the one implementation of the middle road, shared
by train.evaluate and predict.predict_scores: accumulate device arrays,
bulk-``device_get`` every ``chunk`` additions, deliver host arrays to a
consumer in input order.
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax
import numpy as np

# Large enough to amortize the device-link round-trip, small enough to
# bound live device arrays on huge sweeps (256 x [B] f32 ~ 8 MB at
# B=8192).
FETCH_CHUNK_BATCHES = 256


class ChunkedFetcher:
    """``add(device_array, meta)`` accumulates; every ``chunk`` adds (and
    at the final explicit ``flush()``) the pending arrays are fetched in
    ONE ``jax.device_get`` and ``consume(host_array, meta)`` runs for
    each, in add order."""

    def __init__(self, consume: Callable[[np.ndarray, Any], None],
                 chunk: int = FETCH_CHUNK_BATCHES):
        self._consume = consume
        self._chunk = chunk
        self._pending: List[Tuple[Any, Any]] = []

    def add(self, arr, meta: Any = None) -> None:
        self._pending.append((arr, meta))
        if len(self._pending) >= self._chunk:
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        arrs = [a for a, _ in self._pending]
        # device_get on a LIST transfers per-array — N link round-trips.
        # On a proxied device link that multiplies the sweep cost by the
        # chunk arity (measured: a 44-batch predict sweep spent ~9 s in
        # one list-flush, ~200 ms/array). Same-shape device arrays (the
        # scoring case: every batch's [B] scores) are stacked on-device
        # — one compiled concat per (arity, shape), compile-cached —
        # and fetched in ONE transfer, then split host-side for free.
        same_shape = (len(arrs) > 1
                      and all(isinstance(a, jax.Array) for a in arrs)
                      and len({(a.shape, str(a.dtype))
                               for a in arrs}) == 1)
        if same_shape:
            import jax.numpy as jnp
            stacked = np.asarray(jax.device_get(jnp.stack(arrs)))
            fetched: List[Any] = list(stacked)
        else:
            fetched = jax.device_get(arrs)
        for host, (_, meta) in zip(fetched, self._pending):
            self._consume(np.asarray(host), meta)
        self._pending.clear()
