"""Chunked device->host fetches for scoring sweeps.

A PER-BATCH fetch syncs the dispatch pipeline every step — ruinous over
a proxied device link (BASELINE.md "Device-link sync pathology") —
while holding an unbounded sweep's scores grows device memory linearly.
``ChunkedFetcher`` is the one implementation of the middle road, shared
by train.evaluate and predict.predict_scores: accumulate device arrays,
bulk-``device_get`` every ``chunk`` additions, deliver host arrays to a
consumer in input order.
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax
import numpy as np

# Large enough to amortize the device-link round-trip, small enough to
# bound live device arrays on huge sweeps (256 x [B] f32 ~ 8 MB at
# B=8192).
FETCH_CHUNK_BATCHES = 256

# close() gives the worker this long to drain before abandoning it: a
# worker wedged inside a hung device_get (the very stall scenario the
# error path exists for) must not turn teardown into a silent hang
# that masks the propagating exception. An abandoned worker is a
# daemon thread — leaked, but the process stays live and honest.
CLOSE_DRAIN_TIMEOUT_S = 10.0


def bulk_fetch(pairs, consume) -> None:
    """One-shot bulk device->host fetch: ``pairs`` of (value, meta) are
    fetched with the grouped-stacking transfer strategy of
    ChunkedFetcher.flush and delivered to ``consume(host_array, meta)``
    in order. The one entry point for buffered-scalar flushes
    (train.flush_log, ScalarSummaries.flush) — no streaming chunk
    bookkeeping needed."""
    f = ChunkedFetcher(consume, chunk=len(pairs) + 1)
    for value, meta in pairs:
        f.add(value, meta)
    f.flush()


class ChunkedFetcher:
    """``add(device_array, meta)`` accumulates; every ``chunk`` adds (and
    at the final explicit ``flush()``) the pending arrays are fetched in
    ONE ``jax.device_get`` and ``consume(host_array, meta)`` runs for
    each, in add order.

    ``overlap=True`` double-buffers: full chunks are handed to ONE
    background thread that fetches + consumes while the caller keeps
    dispatching the next chunk's device work — without it the consumer
    loop stalls for the whole D2H transfer each chunk (the dominant
    cost of the predict sweep on a tunnelled link, BASELINE.md
    "Predict-path rate"). The queue holds at most one chunk (a second
    full chunk blocks the producer), bounding live device arrays to
    3x chunk (one fetching + one queued + the producer's in-build
    pending list); ``consume`` then runs on the worker thread, in add
    order — callers must not read their accumulator state until
    ``flush()`` returns (both callers aggregate and read only after).
    Worker exceptions re-raise at the next ``add``/``flush``; the
    ``flush`` that re-raises also RESETS the fetcher (queued chunks
    were discarded), so a caller may catch and start a fresh sweep on
    the same instance."""

    def __init__(self, consume: Callable[[np.ndarray, Any], None],
                 chunk: int = FETCH_CHUNK_BATCHES,
                 overlap: bool = False):
        self._consume = consume
        self._chunk = chunk
        self._overlap = overlap
        self._pending: List[Tuple[Any, Any]] = []
        self._queue = None
        self._worker = None
        self._err: List[BaseException] = []
        self._abandon = None  # per-worker Event; set by close()

    @property
    def pending_depth(self) -> int:
        """Entries currently held back for in-order delivery: the
        in-build pending list plus any full chunk queued behind the
        worker. A cheap host-side read for telemetry (the predict
        path's output-order buffer-depth gauge) — approximate by
        design: the worker may be mid-fetch on one more chunk."""
        q = self._queue
        return len(self._pending) + (q.qsize() * self._chunk if q else 0)

    def add(self, arr, meta: Any = None) -> None:
        if self._err:
            # Deliver the worker's error through the same drain + join +
            # clear path flush uses — raising here directly would leave
            # the worker parked on its queue forever and the error
            # sticky, breaking the documented reset-for-reuse contract.
            self.flush()
        self._pending.append((arr, meta))
        if len(self._pending) >= self._chunk:
            self._dispatch()

    def _dispatch(self) -> None:
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        if not self._overlap:
            self._fetch_and_consume(batch)
            return
        if self._worker is None:
            import queue
            import threading
            self._queue = queue.Queue(maxsize=1)
            self._abandon = threading.Event()
            # The worker captures ITS queue/error-list/abandon-flag as
            # arguments: an abandoned worker (close() timed out on a
            # wedged fetch) that later unwedges must only ever touch
            # its own orphaned state — never a reused fetcher's fresh
            # queue or errors.
            self._worker = threading.Thread(
                target=self._worker_loop,
                args=(self._queue, self._err, self._abandon),
                name="fetcher", daemon=True)
            self._worker.start()
        self._queue.put(batch)  # blocks while the previous chunk fetches

    def _worker_loop(self, q, err, abandon) -> None:
        while True:
            batch = q.get()
            try:
                if batch is None:
                    return
                if not err and not abandon.is_set():
                    # after an error (or an abandon-path close), drain
                    # without work
                    self._fetch_and_consume(batch)
            except BaseException as e:  # noqa: BLE001 - re-raised to caller
                err.append(e)
            finally:
                q.task_done()

    def flush(self) -> None:
        """Fetch + consume everything added so far; with overlap, also
        drains and joins the worker so callers may read their
        accumulated results after this returns. On a worker error this
        re-raises it ONCE and leaves the fetcher clean for reuse."""
        self._dispatch()
        if self._worker is not None:
            self._queue.put(None)
            self._worker.join()
            self._worker = None
            self._queue = None
        if self._err:
            e = self._err[0]
            self._err.clear()
            raise e

    def close(self) -> None:
        """Abandon-path teardown, for ``finally`` blocks (ADVICE round
        5): without it, an exception mid-sweep leaves the overlap
        worker parked on ``queue.get`` forever and up to one queued
        chunk of device arrays pinned in device memory for the life of
        the process. Drops pending work, drains + joins the worker, and
        swallows worker errors — an exception is usually already
        propagating, and masking it with a secondary fetch error would
        hide the real failure. Idempotent; a no-op after a clean
        ``flush()``; the fetcher remains reusable."""
        self._pending.clear()
        if self._worker is not None:
            import queue
            import time
            self._abandon.set()
            try:
                # Bounded drain: normally at most one queued chunk
                # precedes the sentinel and the worker drops it fast
                # once abandoned; a worker wedged in a hung fetch never
                # frees the slot, so give up at the deadline rather
                # than hang the error path.
                deadline = time.monotonic() + CLOSE_DRAIN_TIMEOUT_S
                sent = False
                while time.monotonic() < deadline:
                    try:
                        self._queue.put(None, timeout=0.1)
                        sent = True
                        break
                    except queue.Full:
                        continue
                if sent:
                    self._worker.join(
                        timeout=max(0.0, deadline - time.monotonic())
                        + 1.0)
                if self._worker.is_alive():
                    # Abandoned (still wedged): orphan its error list
                    # too — its captured abandon flag stays set, so if
                    # it ever unwedges it drains its own queue and
                    # exits without touching this fetcher again.
                    self._err = []
            finally:
                self._worker = None
                self._queue = None
                self._abandon = None
        self._err.clear()

    def _fetch_and_consume(self, pending) -> None:
        # span (obs/trace; no-op unless the run traces): every bulk
        # D2H — predict/evaluate chunks AND barrier scalar drains —
        # shows up on the timeline, on the thread that paid for it.
        # The always-on fetch/d2h_seconds counter beside it is the D2H
        # share of the fmstat predict attribution (one sample per
        # CHUNK — FETCH_CHUNK_BATCHES batches — not per batch).
        import time
        from fast_tffm_tpu.obs.telemetry import active
        from fast_tffm_tpu.obs.trace import span
        tel = active()
        # fmlint: disable=R003 -- feeds the always-on aggregate; the
        # span beside it is the timeline view
        t0 = time.perf_counter()
        with span("fetch/bulk", n=len(pending)):
            self._fetch_and_consume_inner(pending)
        if tel is not None:
            # fmlint: disable=R003 -- closes the d2h sample
            tel.count("fetch/d2h_seconds", time.perf_counter() - t0)

    def _fetch_and_consume_inner(self, pending) -> None:
        arrs = [a for a, _ in pending]
        # device_get on a LIST transfers per-array — N link round-trips.
        # On a proxied device link that multiplies the sweep cost by the
        # chunk arity (measured: a 44-batch predict sweep spent ~9 s in
        # one list-flush, ~200 ms/array). So: group device arrays by
        # (shape, dtype) and fetch each multi-member group as ONE
        # stacked transfer (one compiled stack per (arity, shape),
        # compile-cached); singletons and non-array values (python
        # floats pass through device_get) ride a single final list
        # fetch. This is the one implementation of the bulk-fetch
        # workaround — train.flush_log and ScalarSummaries.flush route
        # through it rather than hand-rolling variants.
        groups: dict = {}
        for i, a in enumerate(arrs):
            if isinstance(a, jax.Array):
                groups.setdefault((a.shape, str(a.dtype)), []).append(i)
        fetched: dict = {}
        for idxs in groups.values():
            if len(idxs) > 1:
                import jax.numpy as jnp
                try:
                    host = np.asarray(jax.device_get(
                        jnp.stack([arrs[i] for i in idxs])))
                except (ValueError, TypeError):
                    # (shape, dtype) grouping can still collide arrays
                    # on different devices/shardings, which jnp.stack
                    # rejects; fall back to the per-array list fetch for
                    # that group rather than fail the whole flush.
                    continue
                for i, h in zip(idxs, host):
                    fetched[i] = h
        rest = [i for i in range(len(arrs)) if i not in fetched]
        if rest:
            for i, h in zip(rest, jax.device_get([arrs[i] for i in rest])):
                fetched[i] = h
        for i, (_, meta) in enumerate(pending):
            self._consume(np.asarray(fetched[i]), meta)
