"""Chunked device->host fetches for scoring sweeps.

A PER-BATCH fetch syncs the dispatch pipeline every step — ruinous over
a proxied device link (BASELINE.md "Device-link sync pathology") —
while holding an unbounded sweep's scores grows device memory linearly.
``ChunkedFetcher`` is the one implementation of the middle road, shared
by train.evaluate and predict.predict_scores: accumulate device arrays,
bulk-``device_get`` every ``chunk`` additions, deliver host arrays to a
consumer in input order.
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax
import numpy as np

# Large enough to amortize the device-link round-trip, small enough to
# bound live device arrays on huge sweeps (256 x [B] f32 ~ 8 MB at
# B=8192).
FETCH_CHUNK_BATCHES = 256


def bulk_fetch(pairs, consume) -> None:
    """One-shot bulk device->host fetch: ``pairs`` of (value, meta) are
    fetched with the grouped-stacking transfer strategy of
    ChunkedFetcher.flush and delivered to ``consume(host_array, meta)``
    in order. The one entry point for buffered-scalar flushes
    (train.flush_log, ScalarSummaries.flush) — no streaming chunk
    bookkeeping needed."""
    f = ChunkedFetcher(consume, chunk=len(pairs) + 1)
    for value, meta in pairs:
        f.add(value, meta)
    f.flush()


class ChunkedFetcher:
    """``add(device_array, meta)`` accumulates; every ``chunk`` adds (and
    at the final explicit ``flush()``) the pending arrays are fetched in
    ONE ``jax.device_get`` and ``consume(host_array, meta)`` runs for
    each, in add order."""

    def __init__(self, consume: Callable[[np.ndarray, Any], None],
                 chunk: int = FETCH_CHUNK_BATCHES):
        self._consume = consume
        self._chunk = chunk
        self._pending: List[Tuple[Any, Any]] = []

    def add(self, arr, meta: Any = None) -> None:
        self._pending.append((arr, meta))
        if len(self._pending) >= self._chunk:
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        arrs = [a for a, _ in self._pending]
        # device_get on a LIST transfers per-array — N link round-trips.
        # On a proxied device link that multiplies the sweep cost by the
        # chunk arity (measured: a 44-batch predict sweep spent ~9 s in
        # one list-flush, ~200 ms/array). So: group device arrays by
        # (shape, dtype) and fetch each multi-member group as ONE
        # stacked transfer (one compiled stack per (arity, shape),
        # compile-cached); singletons and non-array values (python
        # floats pass through device_get) ride a single final list
        # fetch. This is the one implementation of the bulk-fetch
        # workaround — train.flush_log and ScalarSummaries.flush route
        # through it rather than hand-rolling variants.
        groups: dict = {}
        for i, a in enumerate(arrs):
            if isinstance(a, jax.Array):
                groups.setdefault((a.shape, str(a.dtype)), []).append(i)
        fetched: dict = {}
        for idxs in groups.values():
            if len(idxs) > 1:
                import jax.numpy as jnp
                try:
                    host = np.asarray(jax.device_get(
                        jnp.stack([arrs[i] for i in idxs])))
                except (ValueError, TypeError):
                    # (shape, dtype) grouping can still collide arrays
                    # on different devices/shardings, which jnp.stack
                    # rejects; fall back to the per-array list fetch for
                    # that group rather than fail the whole flush.
                    continue
                for i, h in zip(idxs, host):
                    fetched[i] = h
        rest = [i for i in range(len(arrs)) if i not in fetched]
        if rest:
            for i, h in zip(rest, jax.device_get([arrs[i] for i in rest])):
                fetched[i] = h
        for i, (_, meta) in enumerate(self._pending):
            self._consume(np.asarray(fetched[i]), meta)
        self._pending.clear()
