from fast_tffm_tpu.utils.logging import get_logger  # noqa: F401
from fast_tffm_tpu.utils.timing import StepTimer, trace_span  # noqa: F401
