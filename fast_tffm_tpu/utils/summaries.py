"""TensorBoard scalar summaries — ``save_summaries_steps`` made real.

The reference inherits TF1 summary writing at a configured cadence
(SURVEY.md Appendix A, `save_summaries_steps`). Here the same knob
writes TensorBoard scalars (train loss, examples/sec, validation AUC)
as event files under ``<model_file>.tb/`` via TF's summary writer — TF
is an allowed utility dependency (SURVEY §7: data/AUC utilities, never
the model path). The import is lazy (TF costs ~25 s to load, paid only
when the knob is set) and failure-tolerant: without TF the knob warns
once and training proceeds.

Link-safety: scalar values may be DEVICE arrays; they are buffered
as-is and fetched in one bulk ``jax.device_get`` at ``flush()`` —
called from epoch boundaries, the same barrier the deferred loss log
uses — so summaries add no mid-stream device fetches up to
SUMMARY_BUFFER_MAX retained entries (BASELINE.md "Device-link sync
pathology": one hot-loop scalar fetch costs seconds on a tunnelled
link). An epoch longer than SUMMARY_BUFFER_MAX/2 sampled cadences
pays one bulk mid-epoch fetch per cap hit — the bound on retained
device references is the lesser evil, and README/config state the
same caveat.
"""

from __future__ import annotations

import warnings
from typing import List, Optional, Tuple

from fast_tffm_tpu.config import FmConfig


# Buffered-scalar cap: device references retained between flushes. The
# same bound (and rationale) as train.py's LOG_BUFFER_MAX — a tiny
# cadence on a months-long epoch must not retain unbounded device
# scalars; one rare mid-epoch sync is the lesser evil.
SUMMARY_BUFFER_MAX = 1024


class ScalarSummaries:
    """Buffered TensorBoard scalar writer (see module docstring)."""

    def __init__(self, logdir: str, tf_module):
        self._tf = tf_module
        self._writer = tf_module.summary.create_file_writer(logdir)
        self.logdir = logdir
        self._buf: List[Tuple[str, int, object]] = []

    def add(self, tag: str, step: int, value) -> None:
        """Queue one scalar; ``value`` may be a jax device array (not
        fetched here — see flush)."""
        self._buf.append((tag, step, value))
        if len(self._buf) >= SUMMARY_BUFFER_MAX:
            self.flush()

    def flush(self) -> None:
        if not self._buf:
            return
        # bulk_fetch groups the device scalars into stacked bulk
        # transfers (a per-element list fetch costs a link round-trip
        # EACH on slow links — the exact stall the buffering avoids);
        # python-float values pass through untouched.
        from fast_tffm_tpu.utils.fetch import bulk_fetch
        rows = []
        bulk_fetch([(v, (tag, step)) for tag, step, v in self._buf],
                   lambda v, meta: rows.append((meta[0], meta[1],
                                                float(v))))
        with self._writer.as_default():
            for tag, step, val in rows:
                self._tf.summary.scalar(tag, val, step=step)
        self._writer.flush()
        self._buf.clear()

    def close(self) -> None:
        self.flush()
        self._writer.close()


def make_summaries(cfg: FmConfig) -> Optional[ScalarSummaries]:
    """The train driver's summary sink: a ScalarSummaries under
    ``<model_file>.tb/`` when ``save_summaries_steps`` is set and TF is
    importable, else None (with one warning when the knob asked for
    summaries TF can't provide)."""
    if cfg.save_summaries_steps <= 0:
        return None
    try:
        import tensorflow as tf
    except Exception as e:  # pragma: no cover - env without TF
        warnings.warn(
            f"save_summaries_steps = {cfg.save_summaries_steps} needs "
            f"tensorflow for TensorBoard event files, which failed to "
            f"import ({type(e).__name__}); summaries are disabled for "
            "this run")
        return None
    return ScalarSummaries(cfg.model_file + ".tb", tf)
