"""Step timing + profiling hooks.

The reference has no project-owned profiling (SURVEY.md §5 "Tracing");
here every train step can be wrapped in a ``jax.profiler`` trace
annotation and throughput is measured with ``block_until_ready`` fences.
"""

from __future__ import annotations

import contextlib
import os
import time

import jax


class StepTimer:
    """Examples/sec over the WINDOW since the rate was last read; call
    ``tick(n_examples)`` after each step result is materialised.

    ``consume_window_rate()`` reports and resets the window, so
    consecutive log lines show the rate between logs rather than a
    cumulative average anchored at construction — a cumulative figure
    would absorb first-step jit compilation and every validation/
    checkpoint pause into all later lines, understating the loop rate
    worst on short runs. ``total_examples_per_sec`` keeps the
    whole-run figure (including those pauses) for end-of-run
    summaries."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._t0 = time.perf_counter()
        self._win_t0 = self._t0
        self._examples = 0
        self._win_examples = 0
        self._steps = 0

    def tick(self, n_examples: int) -> None:
        self._examples += n_examples
        self._win_examples += n_examples
        self._steps += 1

    def consume_window_rate(self) -> float:
        """Examples/sec since the previous call, CONSUMING the window —
        an explicit method (not a property) because reading it twice
        per step would silently deflate the second reading."""
        now = time.perf_counter()
        dt = now - self._win_t0
        rate = self._win_examples / dt if dt > 0 else 0.0
        self._win_t0 = now
        self._win_examples = 0
        return rate

    @property
    def total_examples_per_sec(self) -> float:
        dt = time.perf_counter() - self._t0
        return self._examples / dt if dt > 0 else 0.0

    @property
    def steps(self) -> int:
        return self._steps


@contextlib.contextmanager
def trace_span(name: str):
    """jax.profiler annotation; shows up in TensorBoard/Perfetto traces."""
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def profile_to(log_dir: str):
    """Trace the body into ``log_dir`` (created if missing — jax's own
    error for a missing dir is an opaque profiler failure mid-run).

    stop_trace runs EXACTLY once, and only if start_trace succeeded: a
    start_trace that raises (unwritable dir, trace already running)
    must not trigger a stop here — that would either mask the original
    error with "no trace in progress" or, worse, stop an OUTER trace
    the caller still owns."""
    os.makedirs(log_dir, exist_ok=True)
    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
        yield
    finally:
        if started:
            jax.profiler.stop_trace()
