"""Step timing + profiling hooks.

The reference has no project-owned profiling (SURVEY.md §5 "Tracing");
here every train step can be wrapped in a ``jax.profiler`` trace
annotation and throughput is measured with ``block_until_ready`` fences.
"""

from __future__ import annotations

import contextlib
import time

import jax


class StepTimer:
    """Examples/sec over a sliding window of completed steps; call
    ``tick(n_examples)`` after each step result is materialised."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._t0 = time.perf_counter()
        self._examples = 0
        self._steps = 0

    def tick(self, n_examples: int) -> None:
        self._examples += n_examples
        self._steps += 1

    @property
    def examples_per_sec(self) -> float:
        dt = time.perf_counter() - self._t0
        return self._examples / dt if dt > 0 else 0.0

    @property
    def steps(self) -> int:
        return self._steps


@contextlib.contextmanager
def trace_span(name: str):
    """jax.profiler annotation; shows up in TensorBoard/Perfetto traces."""
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def profile_to(log_dir: str):
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
