"""Transient-IO retry with exponential backoff — the data plane's
"survive the survivable" primitive (README "Fault tolerance").

fast_tffm's production niche is multi-epoch training over huge corpora
on networked filesystems, where a single transient ``OSError`` on an
open/read — NFS hiccup, object-store 5xx surfaced through a FUSE
mount, momentary EIO — would otherwise kill a run that has hours of
optimizer state behind it. This module wraps exactly those call sites
(pipeline file opens/reads, weight-sidecar reads, checkpoint
save/restore) in a bounded retry loop:

- **Retryable vs fatal**: ``OSError``/``TimeoutError`` retry, EXCEPT
  the definitely-fatal family (``FileNotFoundError``,
  ``IsADirectoryError``, ``NotADirectoryError``, ``PermissionError``)
  — a missing input file must stay a loud immediate failure
  (pipeline.expand_files' contract), not three backoffs followed by
  the same failure. Everything non-IO (ValueError, ParseError, ...)
  propagates untouched on the first raise.
- **Deterministic jitter**: backoff is ``base * 2^attempt`` scaled by
  a jitter factor drawn from a ``random.Random`` seeded from
  ``(seed, op)`` — reruns back off identically (the fault-injection
  harness pins timing-sensitive behavior), while distinct ops
  de-correlate.
- **Telemetry**: each retry counts ``io/retries`` (+ per-op
  ``io/retries/<op>``) and accumulates ``io/retry_sleep_seconds``;
  the backoff sleep itself is wrapped in an ``obs/trace`` span
  (``io/retry``) so a retry storm is visible on the run timeline.

Knobs: ``io_retries`` / ``io_backoff_seconds`` in ``[Train]``
(config.py), threaded here as a ``RetryPolicy``.
"""

from __future__ import annotations

import dataclasses
import functools
import random
import time
from typing import Callable, Optional, TypeVar

T = TypeVar("T")

# Errors that retrying can never fix: the path itself is wrong (or
# forbidden). FileNotFoundError keeps expand_files' "loud failure on
# missing file" contract intact even with retries enabled.
FATAL_IO_ERRORS = (FileNotFoundError, IsADirectoryError,
                   NotADirectoryError, PermissionError)


def is_retryable(exc: BaseException) -> bool:
    """Whether a retry has any chance of helping: transient-IO classes
    (``OSError``/``TimeoutError``) minus the definitely-fatal family."""
    if isinstance(exc, FATAL_IO_ERRORS):
        return False
    return isinstance(exc, (OSError, TimeoutError))


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How many extra attempts a retryable failure gets, and how long
    the backoff waits. ``retries`` counts attempts AFTER the first
    (0 = current fail-fast behavior); sleep before retry k (0-based)
    is ``backoff_seconds * 2^k * jitter``, jitter uniform in
    [0.5, 1.5) from a ``(seed, op)``-seeded RNG."""
    retries: int = 2
    backoff_seconds: float = 0.1
    seed: int = 0

    @classmethod
    def from_config(cls, cfg) -> "RetryPolicy":
        # getattr defaults: tests and bench build pared-down cfg
        # objects that predate these knobs.
        return cls(retries=getattr(cfg, "io_retries", 2),
                   backoff_seconds=getattr(cfg, "io_backoff_seconds",
                                           0.1),
                   seed=getattr(cfg, "seed", 0))


def _tel():
    from fast_tffm_tpu.obs.telemetry import active
    return active()


def retry_io(fn: Callable[..., T], *args,
             policy: Optional[RetryPolicy] = None, op: str = "io",
             sleep: Callable[[float], None] = time.sleep,
             **kwargs) -> T:
    """Call ``fn(*args, **kwargs)``, retrying retryable IO failures per
    ``policy`` (None = the default RetryPolicy). ``op`` names the call
    site in telemetry and seeds the jitter stream; ``sleep`` is
    injectable so tests pin backoff math without real waits."""
    from fast_tffm_tpu.obs.trace import span
    p = policy or RetryPolicy()
    rng = random.Random(f"{p.seed}/{op}")
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except BaseException as e:
            if not is_retryable(e) or attempt >= p.retries:
                raise
            delay = p.backoff_seconds * (2 ** attempt) * (
                0.5 + rng.random())
            tel = _tel()
            if tel is not None:
                tel.count("io/retries")
                tel.count(f"io/retries/{op}")
                tel.count("io/retry_sleep_seconds", delay)
            # Timeline visibility: the span brackets the backoff wait,
            # carrying the error and attempt index — a retry storm
            # reads as a dense io/retry track in fmtrace.
            with span("io/retry", op=op, attempt=attempt,
                      error=f"{type(e).__name__}: {e}"[:200]):
                if delay > 0:
                    sleep(delay)
            attempt += 1


def retrying(op: str, policy: Optional[RetryPolicy] = None):
    """Decorator form of ``retry_io`` for functions that are retryable
    end-to-end (idempotent reads):

        @retrying("sidecar_read")
        def _read_sidecar(path): ...
    """
    def deco(fn: Callable[..., T]) -> Callable[..., T]:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_io(fn, *args, policy=policy, op=op, **kwargs)
        return wrapper
    return deco


def open_with_retry(path: str, mode: str = "r",
                    policy: Optional[RetryPolicy] = None,
                    op: str = "open", **kwargs):
    """``open()`` with transient-failure retry — the one helper the
    pipeline's file-open sites share so their retry semantics can't
    drift. A missing file still raises ``FileNotFoundError`` on the
    first attempt (fatal class)."""
    return retry_io(open, path, mode, policy=policy, op=op, **kwargs)
