"""Logging — the reference logs ``step, loss`` lines to a cfg-named log
file via Python logging (SURVEY.md §5 "Metrics / logging"); same here,
plus stderr."""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_FMT = "%(asctime)s %(levelname)s %(name)s: %(message)s"


def get_logger(name: str = "fast_tffm_tpu",
               log_file: Optional[str] = None) -> logging.Logger:
    logger = logging.getLogger(name)
    logger.propagate = False  # absl/jax configure the root logger too
    if not logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(_FMT))
        logger.addHandler(h)
    if logger.level == logging.NOTSET:
        # Set the level even when a harness attached its own handler
        # first: NOTSET resolves through the root logger (WARNING),
        # which would silently drop every step/loss INFO line.
        logger.setLevel(logging.INFO)
    if log_file:
        have = {getattr(h, "baseFilename", None) for h in logger.handlers}
        path = os.path.abspath(log_file)
        if path not in have:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            fh = logging.FileHandler(path)
            fh.setFormatter(logging.Formatter(_FMT))
            logger.addHandler(fh)
    return logger
