"""Train driver — the ``py/fm_train.py`` equivalent (SURVEY.md §3.1/§3.2).

Single-process: build state, jit the step, run the hot loop (one device
dispatch per step, Python only loops and logs — the property the
reference gets from ``sess.run`` it gets here from ``jax.jit``).

Distributed: where the reference launches ps/worker roles over TF1's gRPC
runtime with *async* SGD, this framework is synchronous data-parallel over
a device mesh (parallel/), with the table row-sharded across it; the
``dist_train <job> <idx>`` CLI surface is accepted and mapped onto
``jax.distributed`` (parallel/distributed.py).
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import jax
import numpy as np

from fast_tffm_tpu.checkpoint import CheckpointState, export_npz
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import batch_iterator, prefetch
from fast_tffm_tpu.metrics import StreamingAUC
from fast_tffm_tpu.models.fm import (ModelSpec, batch_args, init_accumulator,
                                     init_table, make_score_fn,
                                     make_train_step)
from fast_tffm_tpu.utils.logging import get_logger
from fast_tffm_tpu.utils.timing import StepTimer


def evaluate(cfg: FmConfig, table: jax.Array, files,
             max_batches: Optional[int] = None) -> Tuple[float, int]:
    """Streamed AUC over ``files``; returns (auc, n_examples)."""
    spec = ModelSpec.from_config(cfg)
    score_fn = make_score_fn(spec)
    auc = StreamingAUC()
    n = 0
    for batch in prefetch(batch_iterator(cfg, files, training=False,
                                         epochs=1)):
        args = batch_args(batch)
        args.pop("labels"), args.pop("weights")
        scores = np.asarray(score_fn(table, **args))
        auc.update(scores[:batch.num_real], batch.labels[:batch.num_real])
        n += batch.num_real
        if max_batches and n >= max_batches * cfg.batch_size:
            break
    return auc.result(), n


def train(cfg: FmConfig, job_name: Optional[str] = None,
          task_index: Optional[int] = None) -> jax.Array:
    """Run training per config; returns the final table (host-fetchable).

    ``job_name``/``task_index`` mirror the reference's ``dist_train``
    argv (SURVEY §3.2); in multi-process mode they identify this process
    in the jax.distributed cluster.
    """
    logger = get_logger(log_file=cfg.log_file or None)
    shard_index, num_shards = 0, 1
    if job_name is not None:
        from fast_tffm_tpu.parallel.distributed import init_from_cluster
        shard_index, num_shards = init_from_cluster(cfg, job_name,
                                                    task_index or 0)

    spec = ModelSpec.from_config(cfg)
    table = init_table(cfg, cfg.seed)
    acc = init_accumulator(cfg)
    ckpt = CheckpointState(cfg.model_file)
    global_step = 0
    restored = ckpt.restore(template=checkpoint_template(cfg))
    if restored is not None:
        table = jax.device_put(jnp_like(restored["table"], table))
        acc = jax.device_put(jnp_like(restored["acc"], acc))
        global_step = int(restored["step"])
        logger.info("restored checkpoint at step %d", global_step)

    step_fn = make_train_step(spec)
    timer = StepTimer()
    loss = None
    loss_val = float("nan")
    for epoch in range(cfg.epoch_num):
        for batch in prefetch(batch_iterator(
                cfg, cfg.train_files, training=True,
                weight_files=cfg.weight_files, shard_index=shard_index,
                num_shards=num_shards, epochs=1, seed=cfg.seed + epoch)):
            table, acc, loss, _ = step_fn(table, acc, **batch_args(batch))
            global_step += 1
            timer.tick(batch.num_real)
            if cfg.log_steps and global_step % cfg.log_steps == 0:
                loss_val = float(loss)
                logger.info(
                    "step %d epoch %d loss %.6f examples/sec %.0f",
                    global_step, epoch, loss_val, timer.examples_per_sec)
            if cfg.save_steps and global_step % cfg.save_steps == 0:
                ckpt.save(global_step, table, acc)
        if cfg.validation_files:
            auc, n = evaluate(cfg, table, cfg.validation_files)
            logger.info("epoch %d validation AUC %.6f over %d examples",
                        epoch, auc, n)
    loss_val = float(loss) if loss is not None else loss_val
    ckpt.save(global_step, table, acc, force=True)
    export_npz(table, cfg.model_file + ".npz",
               vocabulary_size=cfg.vocabulary_size)
    logger.info("training done: %d steps, final loss %.6f, %.0f examples/sec",
                global_step, loss_val, timer.examples_per_sec)
    ckpt.close()
    return table


def jnp_like(host_arr, like: jax.Array):
    import jax.numpy as jnp
    return jnp.asarray(np.asarray(host_arr), dtype=like.dtype)


def checkpoint_template(cfg: FmConfig):
    """Abstract pytree matching CheckpointState.save's layout — orbax
    needs it to restore from a process that didn't do the saving."""
    shape = (cfg.num_rows, cfg.row_dim)
    return {"table": jax.ShapeDtypeStruct(shape, np.float32),
            "acc": jax.ShapeDtypeStruct(shape, np.float32),
            "step": 0}
