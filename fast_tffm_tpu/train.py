"""Train driver — the ``py/fm_train.py`` equivalent (SURVEY.md §3.1/§3.2).

Single-process: build state, jit the step, run the hot loop (one device
dispatch per step, Python only loops and logs — the property the
reference gets from ``sess.run`` it gets here from ``jax.jit``).

Distributed: where the reference launches ps/worker roles over TF1's gRPC
runtime with *async* SGD, this framework is synchronous data-parallel over
a device mesh (parallel/), with the table row-sharded across it; the
``dist_train <job> <idx>`` CLI surface is accepted and mapped onto
``jax.distributed`` (parallel/distributed.py).
"""

from __future__ import annotations

import contextlib
import signal
import time
from typing import Optional, Tuple

import jax
import numpy as np

from fast_tffm_tpu.checkpoint import CheckpointState, export_npz
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.badlines import BadLineTracker
from fast_tffm_tpu.data.pipeline import (SPILL_WARN_FRACTION, SpillStats,
                                         batch_iterator,
                                         gil_bound_iteration,
                                         host_parallel_workers, prefetch,
                                         uniq_bucket_top)
from fast_tffm_tpu.utils.retry import RetryPolicy
from fast_tffm_tpu.metrics import StreamingAUC
from fast_tffm_tpu.models.fm import (ModelSpec, batch_args, init_accumulator,
                                     init_table, make_batch_scorer,
                                     make_train_step, ships_raw_batches)
from fast_tffm_tpu.obs.memory import (LEDGER, oom_guard,
                                      preflight_capacity, table_bytes)
from fast_tffm_tpu.obs.telemetry import (active, make_telemetry,
                                         pop_active, push_active)
from fast_tffm_tpu.obs.trace import span
from fast_tffm_tpu.utils.fetch import ChunkedFetcher, bulk_fetch
from fast_tffm_tpu.utils.logging import get_logger
from fast_tffm_tpu.utils.timing import StepTimer, trace_span


# First-log-step probe threshold (train()): a materialized-scalar fetch
# slower than this marks the device link as slow and defers loss log
# lines to epoch boundaries. Module-level so tests can force either
# mode.
LIVE_FETCH_BUDGET_S = 0.005

# Deferred-mode loss-log buffer cap: scalar device arrays retained
# between flushes. Deliberately its own constant — FETCH_CHUNK_BATCHES
# is tuned for bulk [B]-score memory, and retuning that must not change
# how often a slow link pays a mid-epoch log sync.
LOG_BUFFER_MAX = 1024


def evaluate(cfg: FmConfig, table: jax.Array, files,
             max_batches: Optional[int] = None,
             mesh=None, backend=None,
             weight_files=(), bad_lines=None,
             vocab=None, collect=None) -> Tuple[float, int]:
    """Streamed AUC over ``files``; returns (auc, n_examples). Pass the
    training mesh to score a row-sharded table in place, or a lookup
    ``backend`` (lookup.HostOffloadLookup) to score a host-offloaded
    table (``table`` is then unused). ``weight_files`` (sidecars
    parallel to ``files``) weight each example's AUC contribution the
    same way training weights its loss. ``bad_lines``: the caller's
    run-scoped BadLineTracker — train() shares its tracker so
    per-epoch validation sweeps don't quarantine the same bad line
    once per epoch through fresh dedupe sets. ``collect`` (an
    obs/quality.QualityStats or anything with the same
    ``update(scores, labels, weights)`` surface) is fed the SAME host
    score chunks the AUC update consumes — the publish-gate quality
    loop's zero-added-device-fetch seam."""
    spec = ModelSpec.from_config(cfg)
    score_fn = make_batch_scorer(spec, mesh=mesh, backend=backend)
    raw = ships_raw_batches(spec, mesh=mesh, backend=backend)
    if vocab is not None:
        # Telemetry-silent snapshot: a held-out sweep's unique tail is
        # disproportionately unadmitted and would otherwise inflate
        # the training stream's cold-hit rate (the COLD-ROW SATURATION
        # verdict's input).
        vocab = vocab.eval_view()
    auc = StreamingAUC()
    n = 0
    n_batches = 0

    def _consume(scores, m):
        s, y, w = scores[:m[1]], m[0][:m[1]], m[2][:m[1]]
        auc.update(s, y, w)
        if collect is not None:
            collect.update(s, y, w)

    # Chunked fetches (utils/fetch.py): per-batch syncs are ruinous over
    # a tunnelled link, whole-sweep buffering is unbounded.
    fetcher = ChunkedFetcher(
        _consume,
        overlap=True)  # D2H of chunk N overlaps scoring of chunk N+1
    tel = active()
    # try/finally (ADVICE round 5): an exception mid-sweep must not
    # leave the overlap worker parked on queue.get forever with a
    # queued chunk of device score arrays pinned in HBM — close()
    # drains and joins the worker without masking the original error.
    try:
        for batch in prefetch(batch_iterator(cfg, files, training=False,
                                             weight_files=weight_files,
                                             epochs=1, raw_ids=raw,
                                             bad_lines=bad_lines,
                                             vocab=vocab),
                              depth=cfg.prefetch_depth,
                              gil_bound=gil_bound_iteration(
                                  cfg, weight_files)):
            args = batch_args(batch)
            args.pop("labels"), args.pop("weights")
            fetcher.add(score_fn(table, args),
                        (batch.labels, batch.num_real, batch.weights))
            n += batch.num_real
            n_batches += 1
            if tel is not None:
                # A full validation sweep can outlast the watchdog's
                # stall budget; scored batches are progress.
                tel.heartbeat()
            # Batch-count cap — the same per-input-shard unit the
            # distributed path uses, so AUC samples are comparable.
            if max_batches and n_batches >= max_batches:
                break
        fetcher.flush()
    finally:
        fetcher.close()
    return auc.result(), n


def evaluate_distributed(cfg: FmConfig, table: jax.Array, files, mesh,
                         shard_index: int, num_shards: int,
                         uniq_bucket: int = 0,
                         max_batches: Optional[int] = None,
                         weight_files=(),
                         bad_lines=None,
                         preempt=None, collect=None) -> Tuple[float, int]:
    """Multi-process sharded AUC: every process scores its own input
    shard through the mesh score fn in lockstep (the shared
    lockstep_score_batches protocol), then the per-process binned-AUC
    histograms are allgathered and merged — no table or score set ever
    materializes on one host. Returns the same (auc, n_examples) on
    every process. ``max_batches`` caps real batches per input shard.

    ``uniq_bucket``: pass the caller's once-probed value; 0 re-probes
    (deterministic — same bytes on every process, so all agree without
    a collective). ``preempt`` rides the lockstep fill allgather
    (parallel/sharded.py): a SIGTERM on one worker stops the sweep on
    EVERY worker at the same window boundary — the partial histograms
    still merge below (everyone exits the loop together, so the final
    allgather stays matched). ``collect`` (obs/quality.QualityStats):
    fed the per-batch local scores like the AUC update, and its four
    sums ride INSIDE the existing histogram-merge allgather payload —
    the quality loop adds no collective and no device fetch; after the
    merge the collector holds the job-wide totals. Its presence is
    config-deterministic, so every process ships the same payload
    width."""
    import numpy as np
    from jax.experimental import multihost_utils
    from fast_tffm_tpu.data.pipeline import probe_uniq_bucket
    from fast_tffm_tpu.parallel.liveness import guarded_collective
    from fast_tffm_tpu.parallel.sharded import (lockstep_score_batches,
                                                make_sharded_score_fn)
    spec = ModelSpec.from_config(cfg)
    score_fn = make_sharded_score_fn(spec, mesh)
    auc = StreamingAUC()
    n = 0
    ub = uniq_bucket or cfg.uniq_bucket or probe_uniq_bucket(cfg, files)
    it = batch_iterator(cfg, files, training=False, epochs=1,
                        weight_files=weight_files,
                        shard_index=shard_index, num_shards=num_shards,
                        fixed_shape=True, uniq_bucket=ub,
                        bad_lines=bad_lines)
    for batch, local in lockstep_score_batches(cfg, it, mesh, score_fn,
                                               table, ub,
                                               max_batches=max_batches,
                                               preempt=preempt):
        nr = batch.num_real
        auc.update(local[:nr], batch.labels[:nr], batch.weights[:nr])
        if collect is not None:
            collect.update(local[:nr], batch.labels[:nr],
                           batch.weights[:nr])
        n += batch.num_real
    # process_allgather device_puts its payload and this runtime never
    # enables x64, so float64 histograms (and int64 counts) silently
    # downcast to 32 bits in transit — bins past 2^24 examples lose
    # integer precision and a per-process n past 2^31 wraps, both real
    # at the Criteo-1TB north star. Ship every f64 value as a (hi, lo)
    # float32 pair (lo = v - f64(f32(v))): hi + lo recovers ~48 bits
    # exactly, enough for any count this side of 10^14.
    bins = auc.num_bins
    # The quality collector's four sums ride the same payload (its
    # presence is config-driven, so every process agrees on the
    # width) — the publish-gate quality loop adds zero collectives.
    extra = (collect.sums() if collect is not None
             else np.zeros(0, np.float64))
    payload = np.concatenate([auc.pos, auc.neg,
                              np.asarray([n], np.float64), extra])
    width = 2 * bins + 1 + extra.shape[0]
    hi = payload.astype(np.float32)
    lo = (payload - hi.astype(np.float64)).astype(np.float32)
    gathered = guarded_collective(
        multihost_utils.process_allgather,
        np.stack([hi, lo]),
        label="validation/auc_merge")          # [P, 2, width] f32
    gathered = gathered.reshape(-1, 2, width)
    vals = (gathered[:, 0, :].astype(np.float64)
            + gathered[:, 1, :].astype(np.float64)).sum(axis=0)
    merged = StreamingAUC(num_bins=bins)
    merged.pos[:] = vals[:bins]
    merged.neg[:] = vals[bins:2 * bins]
    n_total = int(round(vals[2 * bins]))
    if collect is not None:
        collect.load_sums(vals[2 * bins + 1:])
    return merged.result(), n_total


class ClusterGrowth(Exception):
    """Control-flow signal out of ``_train_session`` at a safe barrier
    (epoch boundary / publish settle): the chief planned admission of
    replacement worker(s) — ``plan`` is the ``liveness.plan_grow``
    payload — and the barrier state is durably saved, so the elastic
    driver can tear the session down cleanly and reform the grown
    cluster. NOT an error: it must never be recorded as a crash."""

    def __init__(self, plan: dict):
        super().__init__(f"cluster growth planned: generation "
                         f"{plan.get('generation')}")
        self.plan = plan


class _GrowContext:
    """Driver-owned elastic-grow state threaded into the session
    (``elastic = grow``): the CURRENT membership + generation (which
    only the driver's reforms move) and the safe-barrier admission
    check. ``capacity`` is the original cluster size — joiners fill
    the ORIGINAL indices of departed workers, so a healed cluster is
    indistinguishable from one that never shrank."""

    def __init__(self, cfg: FmConfig, lease, members, generation: int):
        self.cfg = cfg
        self.lease = lease
        self.members = tuple(int(m) for m in members)
        self.generation = int(generation)
        self.capacity = max(len(cfg.worker_hosts), 1)

    def adopt(self, members, generation: int) -> None:
        self.members = tuple(int(m) for m in members)
        self.generation = int(generation)

    def check_barrier(self) -> Optional[dict]:
        """The admission check every safe barrier runs: fresh join
        tickets against free original slots -> the next generation's
        plan, or None. Every process runs the same scan and the
        chief's answer is broadcast (identity single-process), so a
        ticket appearing mid-scan can never diverge the cluster —
        all workers raise ClusterGrowth together or nobody does."""
        if self.lease is None or len(self.members) >= self.capacity:
            return None
        from fast_tffm_tpu.parallel import liveness as lv
        tickets = lv.pending_join_tickets(self.lease.directory,
                                          self.lease.stale_after)
        plan = lv.plan_grow(self.generation + 1, self.members,
                            self.capacity, tickets)
        if jax.process_count() > 1:
            from fast_tffm_tpu.data.stream import broadcast_blob
            plan = broadcast_blob(plan, "cluster/grow_decision")
        return plan


def train(cfg: FmConfig, job_name: Optional[str] = None,
          task_index: Optional[int] = None,
          join: bool = False) -> jax.Array:
    """Run training per config; returns the final table (host-fetchable).

    ``job_name``/``task_index`` mirror the reference's ``dist_train``
    argv (SURVEY §3.2); in multi-process mode they identify this process
    in the jax.distributed cluster.

    This is the elastic driver around ``_train_session`` (the actual
    training loop): it owns run-scoped state that must SURVIVE a
    compute-plane recovery — the telemetry stream (one run segment per
    invocation, so worker_lost diagnoses and the recovery both land in
    the same fmstat view), the bad-line tracker (quarantine dedupe
    spans recoveries like it spans epochs), the heartbeat lease, and
    the collective deadline guard. On ``WorkerLostError`` with
    ``elastic = shrink`` the survivors tear the distributed client
    down, reform the cluster from the surviving lease holders
    (``reform_shrunken_cluster``), and re-enter the session — which
    restores from the last verified checkpoint and redistributes the
    lost worker's input shards by re-sharding over the shrunken
    membership. With ``elastic = off`` the error (naming the dead
    peers) propagates: fail fast, never hang.

    ``elastic = grow`` adds the healing direction: the session checks
    for join-request leases at every safe barrier and raises
    ``ClusterGrowth`` (after durably saving the barrier state) when a
    replacement can be admitted — the driver reforms the GROWN cluster
    and re-enters, and the newcomer restores through the same verified
    checkpoint + chief-broadcast path every member uses.

    ``join = True`` is the replacement process itself
    (``run_tffm.py train <cfg> --join``): it rendezvouses into a
    running cluster FIRST (its worker slot is unknown until admitted),
    then runs this same driver loop as an ordinary member."""
    from fast_tffm_tpu.parallel.liveness import (
        HeartbeatLease, WorkerLostError, install_guard, lease_dir,
        restore_guard)
    logger = get_logger(log_file=cfg.log_file or None)
    join_info = None
    if join:
        if cfg.elastic != "grow":
            raise ValueError(
                "train --join requires elastic = grow in [Cluster]: "
                "the running cluster only scans for join tickets when "
                "grow is on")
        if job_name is not None:
            raise ValueError("train --join replaces the dist_train "
                             "role argv: the worker slot is assigned "
                             "by the running cluster, not the launcher")
        from fast_tffm_tpu.parallel.distributed import join_rendezvous
        # Admission BEFORE telemetry: the metrics shard is keyed by
        # the worker slot the cluster assigns, which does not exist
        # until the rendezvous commits.
        join_info = join_rendezvous(cfg, logger)
    # Telemetry BEFORE the cluster join, keyed by the launcher-assigned
    # task index (jax.process_index() is not valid yet): a job that
    # never forms still writes its `health: cluster_bringup_failed`
    # post-mortem into the stream, and elastic recoveries later stay
    # inside this one run segment.
    tel = make_telemetry(cfg, "train",
                         process_index=(join_info[5] if join_info
                                        else (task_index or 0))
                         if (job_name is not None or join_info)
                         else None,
                         process_count=max(len(cfg.worker_hosts), 1)
                         if (job_name is not None or join_info)
                         else None)
    if tel is not None:
        logger.info(
            "writing run metrics to %s (flush every %s steps; summarize "
            "with: python -m tools.fmstat %s)", tel.sink.path,
            tel.flush_steps or "epoch", tel.sink.path)
        # Stamp the configured SLO spec into the stream as slo/*
        # gauges, so `fmstat slo` renders the PASS/FAIL table from the
        # JSONL alone — no config file needed at read time (obs/slo.py).
        from fast_tffm_tpu.obs.slo import SloSpec
        SloSpec.from_config(cfg).emit_gauges(tel)
    # One run-scoped tracker (None under bad_line_policy = error): the
    # max_bad_fraction breaker and the quarantine dedupe must see the
    # WHOLE run — every epoch AND every elastic recovery
    # (data/badlines.py).
    bad_tracker = BadLineTracker.from_config(cfg)
    tel_prev = push_active(tel)  # popped in the finally, crash or not
    lease = None
    guard_prev = None
    guard_installed = False
    try:
        # Pre-flight capacity check (obs/memory.py): when the backend
        # reports a device capacity, a config whose PREDICTED resident
        # bytes exceed it is refused here with the planner's per-owner
        # breakdown — not minutes later as a raw XLA OOM. No-op when
        # capacity is unmeasured (the CPU container).
        preflight_capacity(cfg, "train")
        shard_index, num_shards = 0, 1
        generation = 0
        members = [0]
        if join_info is not None:
            lease, shard_index, num_shards, members, generation, _ = \
                join_info
            if tel is not None:
                tel.lease = lease
                tel.sink.meta.update(
                    backend=jax.default_backend(),
                    device_count=jax.device_count(),
                    process_count=jax.process_count())
        elif job_name is not None:
            from fast_tffm_tpu.parallel.distributed import init_from_cluster
            shard_index, num_shards = init_from_cluster(cfg, job_name,
                                                        task_index or 0)
            members = list(range(num_shards))
            if tel is not None:
                # The meta was stamped pre-join with the LOCAL backend
                # view (deliberate: bring-up failures must land in the
                # stream); refresh it in place so every subsequent
                # event's `run` field carries the real topology.
                tel.sink.meta.update(
                    backend=jax.default_backend(),
                    device_count=jax.device_count(),
                    process_count=jax.process_count())
        if (join_info is None and num_shards > 1
                and cfg.heartbeat_seconds > 0):
            lease = HeartbeatLease(
                lease_dir(cfg), process_index=shard_index,
                members=range(num_shards),
                heartbeat_seconds=cfg.heartbeat_seconds).start()
            if tel is not None:
                tel.lease = lease
        if num_shards > 1:
            guard_prev = install_guard(
                lease, cfg.collective_timeout_seconds)
            guard_installed = True
        grow_ctx = (_GrowContext(cfg, lease, members, generation)
                    if cfg.elastic == "grow" and lease is not None
                    else None)
        while True:
            try:
                return _train_session(cfg, logger, tel, bad_tracker,
                                      shard_index, num_shards,
                                      grow_ctx=grow_ctx)
            except ClusterGrowth as g:  # fmlint: disable=R014 -- cluster-wide arm, see below
                # R014: ClusterGrowth is raised off the chief-broadcast
                # grow plan at the admission barrier, so every incumbent
                # takes this arm on the same iteration, and
                # reform_grown_cluster re-synchronizes the collective
                # protocol state before the session restarts.
                # fmlint: disable=R001 -- plan fields are parsed JSON
                # host values (liveness.plan_grow), never device arrays
                generation = int(g.plan["generation"])
                # fmlint: disable=R001 -- same host-JSON plan fields
                planned = sorted(int(s)
                                 for s in g.plan["joiners"].values())
                logger.info(
                    "elastic grow: admitting joiner(s) %s into "
                    "cluster generation %d (barrier state saved)",
                    planned, generation)
                # Disarm the deadline sentinel like the shrink path:
                # no guarded collective completes during a reform.
                if guard_installed:
                    restore_guard(guard_prev)
                    guard_installed = False
                from fast_tffm_tpu.parallel import liveness as lv
                from fast_tffm_tpu.parallel.distributed import (
                    reform_grown_cluster)
                try:
                    if num_shards <= 1 or jax.process_index() == 0:
                        # The plan file is what the JOINER polls for —
                        # the incumbents already share it (chief-
                        # broadcast at the barrier).
                        lv.write_grow_plan(lease.directory, g.plan)
                    # The returned generation is authoritative: the
                    # dead-committed-joiner fallback reforms one past
                    # the plan's, and reusing a consumed generation
                    # would collide with its still-bound coordinator
                    # port on the next reform.
                    shard_index, num_shards, members, generation = \
                        reform_grown_cluster(cfg, lease, generation,
                                             g.plan, logger)
                except BaseException as re:
                    _record_crash(tel, logger, re)
                    raise
                grow_ctx.adopt(members, generation)
                from fast_tffm_tpu.obs.health import (
                    emit_elastic_recovery)
                # fmlint: disable=R001 -- host-JSON plan fields
                incumbents = {int(i) for i in g.plan["incumbents"]}
                joined = sorted(set(members) - incumbents)
                emit_elastic_recovery(
                    generation, members, lost=[], joined=joined,
                    capacity=grow_ctx.capacity, kind="grow")
                logger.info(
                    "elastic recovery complete: %d member(s) "
                    "(admitted %s), input shards re-balanced, "
                    "resuming from the last verified checkpoint",
                    num_shards, joined or "nobody")
                if num_shards > 1:
                    guard_prev = install_guard(
                        lease, cfg.collective_timeout_seconds)
                    guard_installed = True
            except WorkerLostError as e:  # fmlint: disable=R014 -- survivor-wide arm, see below
                # R014: every survivor's deadline guard raises off the
                # same stale lease entry, so the survivors take this arm
                # together; the non-elastic path re-raises (fail fast)
                # and the elastic path re-forms the cluster, which
                # re-synchronizes the protocol state from scratch.
                if (cfg.elastic not in ("shrink", "grow")
                        or num_shards <= 1 or lease is None):
                    _record_crash(tel, logger, e)
                    # Fail FAST: retire (never shutdown — its barrier
                    # cannot complete with a dead peer) so interpreter
                    # exit isn't stalled by the doomed handshake.
                    from fast_tffm_tpu.parallel.distributed import (
                        retire_distributed_client)
                    retire_distributed_client()
                    raise
                generation += 1
                logger.warning(
                    "worker lost (%s); elastic shrink recovery, "
                    "cluster generation %d", e, generation)
                lost_ids = sorted({i.process_index for i in e.lost})
                # Disarm the deadline sentinel for the reform: no
                # guarded collective completes while the cluster is
                # down, and the dead peer stays stale — the sentinel
                # would otherwise read the (healthy, bounded) reform
                # as a hang and hard-exit mid-recovery.
                if guard_installed:
                    restore_guard(guard_prev)
                    guard_installed = False
                from fast_tffm_tpu.parallel.distributed import (
                    reform_shrunken_cluster)
                try:
                    shard_index, num_shards, members = \
                        reform_shrunken_cluster(cfg, lease, generation,
                                                logger)
                except BaseException as re:
                    _record_crash(tel, logger, re)
                    raise
                from fast_tffm_tpu.obs.health import emit_elastic_recovery
                emit_elastic_recovery(
                    generation, members, lost_ids,
                    capacity=max(len(cfg.worker_hosts), 1))
                if grow_ctx is not None:
                    grow_ctx.adopt(members, generation)
                logger.info(
                    "elastic recovery complete: %d survivor(s), input "
                    "shards redistributed, resuming from the last "
                    "verified checkpoint", num_shards)
                if num_shards > 1:
                    # Re-arm for the shrunken cluster (the lease's
                    # expected membership was updated by the reform).
                    guard_prev = install_guard(
                        lease, cfg.collective_timeout_seconds)
                    guard_installed = True
                elif grow_ctx is None:
                    # Lone survivor: no peers left to guard against;
                    # stop the lease so the next multi-worker run in
                    # this rendezvous dir starts from a clean table.
                    # (elastic = grow keeps it: joiners verify
                    # incumbent liveness through it, and the grow
                    # barrier scan reads join tickets beside it.)
                    lease.stop()
                    if tel is not None:
                        tel.lease = None
                    lease = None
    except BaseException as e:
        # Crash forensics for everything the session didn't already
        # record (it records its own loop crashes with the step
        # attached; WorkerLostError and reform failures are recorded
        # above). record_crash is idempotent per event stream read —
        # but avoid double events: only record here if the session
        # never did (it marks recorded exceptions).
        if tel is not None and not getattr(e, "_fm_crash_recorded",
                                           False):
            _record_crash(tel, logger, e)
        raise
    finally:
        if lease is not None:
            try:
                lease.stop()
            except Exception:
                logger.exception("heartbeat lease stop failed")
        if guard_installed:
            restore_guard(guard_prev)
        if tel is not None:
            try:
                tel.close()
            except Exception:
                logger.exception("metrics sink close failed")
        if bad_tracker is not None:
            try:
                bad_tracker.close()
            except Exception:
                logger.exception("quarantine file close failed")
        pop_active(tel_prev)


def _record_crash(tel, logger, e: BaseException, step: int = -1) -> None:
    """Best-effort crash event, marking the exception so the outer
    driver doesn't write it twice."""
    if tel is None or getattr(e, "_fm_crash_recorded", False):
        return
    try:
        tel.record_crash(e, step)
        e._fm_crash_recorded = True
    except Exception:
        logger.exception("crash event emission failed")


def _train_session(cfg: FmConfig, logger, tel, bad_tracker,
                   shard_index: int, num_shards: int,
                   grow_ctx=None) -> jax.Array:
    """One training session against the CURRENT cluster membership:
    mesh build, checkpoint restore, the epoch/step loop, and the final
    save/export. Raises ``WorkerLostError`` out of any guarded
    collective when a peer dies — the elastic driver (``train``) owns
    what happens next — and ``ClusterGrowth`` out of a safe barrier
    when ``grow_ctx`` plans an admission (the barrier state is saved
    first, so the newcomer restores exactly this point). Everything
    created here (checkpoint manager, summaries, signal handlers,
    profiler) is torn down here, so the driver can safely re-enter
    after a recovery."""
    spec = ModelSpec.from_config(cfg)
    multi_process = jax.process_count() > 1
    stream_mode = getattr(cfg, "run_mode", "epochs") == "stream"
    offload = cfg.lookup == "host"
    if offload and multi_process:
        # Design position, not a gap: any multi-host v5e job has >= 8
        # chips, whose aggregate HBM covers config #5's 72 GB state
        # row-sharded (BASELINE.md "Design note: multi-host beyond-HBM
        # is covered by the mesh"); a cross-process host-RAM table would
        # re-implement the mesh with a slower transport.
        raise ValueError(
            "lookup = host is single-process by design: multi-host scale "
            "uses the row-sharded mesh (lookup = device) — see "
            "BASELINE.md's multi-host beyond-HBM design note")
    mesh = None
    if jax.device_count() > 1 and not offload:
        # More than one device (one host of a TPU slice, or the whole
        # jax.distributed job): row-shard the table over the global mesh
        # and data-shard the batch (parallel/sharded.py). One device:
        # the plain jitted step, no mesh machinery.
        from fast_tffm_tpu.parallel.sharded import (
            global_batch, init_sharded_state, make_mesh,
            make_sharded_train_step, shard_batch)
        mesh = make_mesh()
        logger.info("mesh training: %s over %d devices, %d processes",
                    dict(mesh.shape), jax.device_count(),
                    jax.process_count())

    if multi_process:
        from fast_tffm_tpu.data.pipeline import require_bounded_examples
        require_bounded_examples(cfg, "multi-process training")
    raw_mode = spec.dedup == "device"
    if raw_mode and (mesh is not None or multi_process):
        # Unreachable via dedup=auto (it resolves to host whenever more
        # than one device exists); an explicit config gets a clear error.
        raise ValueError(
            "dedup = device is single-device only: mesh and multi-process "
            "paths rely on the host-side unique contract (fixed-U "
            "buckets, global_batch local_idx offsets)")

    # Run telemetry (tel) and the bad-line tracker arrive from the
    # elastic driver (train()): both are run-scoped — they must span
    # every session a recovery re-enters, so the driver owns their
    # lifecycle and this session only feeds them.
    # Names the finally below reads; they must exist even when setup
    # raises before reaching their real definitions.
    summaries = None
    profiling = False
    prev_handlers = {}
    global_step = 0
    ckpt = None

    def flush_log():  # rebound once the deferred log buffer exists
        pass

    worker_lost = False
    try:
        # Visibility only — the plane lives inside batch_iterator.
        # host_parallel_workers is the SAME predicate the routing
        # uses, so this log never claims a fan-out the pipeline won't
        # perform for THIS run's inputs (C++ missing, weight sidecars,
        # tolerant fixed-shape all route serial).
        host_workers = host_parallel_workers(
            cfg, cfg.weight_files, fixed_shape=multi_process)
        if host_workers > 1 and not stream_mode:
            logger.info(
                "host data plane: %d parallel batch-build workers "
                "(host_threads = %s; bounded ordered ring)",
                host_workers, cfg.host_threads)
        uniq_bucket = 0
        if multi_process and not stream_mode:
            # Fixed-shape batches need one U for the whole job. Auto mode
            # measures the data (probe is deterministic and identical on
            # every process) instead of assuming the next_pow2(B*L) worst
            # case — a ~50x smaller gather/scatter per step at Criteo-like
            # density; denser-than-probed batches spill, never break.
            # (Stream mode probes the discovered SEALED shards instead,
            # chief-decided — data/stream.probe_stream_uniq_bucket.)
            from fast_tffm_tpu.data.pipeline import probe_uniq_bucket
            uniq_bucket = cfg.uniq_bucket or probe_uniq_bucket(
                cfg, cfg.train_files)
            logger.info("fixed unique-row bucket: %d", uniq_bucket)
        val_bucket = 0
        if multi_process and cfg.validation_files:
            val_bucket = cfg.uniq_bucket or probe_uniq_bucket(
                cfg, cfg.validation_files)

        # Vocabulary admission (README "Unbounded vocabulary";
        # fast_tffm_tpu/vocab/): the runtime owns the sketch + slot
        # map; the data plane builds batches in the hashed space and
        # remaps through it; barriers run at the existing epoch/
        # publish synchronization points below.
        vocab = None
        if getattr(cfg, "vocab_mode", "fixed") == "admit":
            if multi_process:
                raise ValueError(
                    "vocab_mode = admit is single-process: the slot "
                    "map is host state, and lockstep workers would "
                    "need a chief-broadcast admission protocol to "
                    "agree on it (ROADMAP item 3's sharded-table "
                    "leg). Run admit-mode training on one process.")
            from fast_tffm_tpu.vocab.table import VocabRuntime
            vocab = VocabRuntime.from_config(cfg)
            logger.info(
                "vocab admission: %d physical rows (row 0 = shared "
                "cold row) over a 2^30 hashed id space; admit/evict "
                "threshold %.1f, decay %.2f/barrier, sketch %.1f MB",
                cfg.vocabulary_size, cfg.vocab_admit_threshold,
                cfg.vocab_decay, cfg.vocab_sketch_mb)

        ckpt = CheckpointState(cfg.model_file,
                               retry=RetryPolicy.from_config(cfg),
                               verify=getattr(cfg, "ckpt_verify", "size"))
        global_step = 0
        restored = ckpt.restore(
            template=checkpoint_template(cfg, mesh, host=offload))
        restored_epoch = 0
        if restored is not None:
            check_restored_vocab(cfg, restored)
            global_step = int(restored["step"])
            restored_epoch = int(restored["epoch"])
            logger.info("restored checkpoint at step %d", global_step)
        vocab_fresh_over_restore = False
        if vocab is not None and restored is not None:
            payload = restored.get("vocab_admission")
            if payload is None:
                logger.warning(
                    "restored checkpoint at step %d carries no vocab "
                    "admission sidecar (a fixed-mode warm start, or a "
                    "lost/garbled sidecar): admission state starts "
                    "FRESH — previously admitted ids serve from the "
                    "cold row until they re-cross the threshold",
                    global_step)
                # The restored table still holds the LOST mapping's
                # trained rows; fresh admission must not hand them to
                # new owners (see the cold-start reset below, once the
                # table is materialized).
                vocab_fresh_over_restore = True
            else:
                vocab.load(cfg, payload)
                logger.info(
                    "restored vocab admission state at step %d: %d "
                    "live rows", global_step, vocab.live_rows)
        elif restored is not None:
            from fast_tffm_tpu.checkpoint import (
                refuse_fixed_mode_admit_step)
            refuse_fixed_mode_admit_step(
                cfg, ckpt.directory, global_step,
                payload=restored.get("vocab_admission"))
        restored_step = global_step
        start_epoch = resume_start_epoch(restored_epoch, cfg.epoch_num)
        if start_epoch:
            logger.info("resuming interrupted epoch schedule at epoch %d/%d",
                        start_epoch, cfg.epoch_num)
        lk = None
        if offload:
            # Offload backend (lookup.py; BASELINE config #5): the table/
            # accumulator live outside HBM. make_offload_backend picks the
            # in-jit pinned-host implementation (whole step stays in the
            # async dispatch stream) where the backend compiles it, else the
            # numpy fallback with its inherent per-step gradient fetch.
            from fast_tffm_tpu.lookup import (PinnedHostLookup,
                                              make_offload_backend,
                                              make_offload_train_step)
            lk = make_offload_backend(cfg, cfg.seed, restored=restored)
            if restored is not None:
                # The backend adopted the arrays (numpy backend: zero-copy)
                # or copied them into accelerator-host memory (pinned
                # backend); keeping these references for the rest of
                # train() would pin a SECOND full table+accumulator in
                # local RAM for the whole resumed run — a sustained 2x that
                # is an OOM at config-#5 scale (the same concern
                # HostOffloadLookup.load documents for transient copies).
                restored["table"] = restored["acc"] = None
            kind = (f"pinned-host in-jit ({lk.mode})"
                    if isinstance(lk, PinnedHostLookup) else "host-numpy")
            logger.info("offload lookup [%s]: table [%d, %d] outside HBM "
                        "(%.2f GB + accumulator)", kind, lk.rows, lk.dim,
                        lk.rows * lk.dim * 4 / 2**30)
            offload_step = make_offload_train_step(spec, lk,
                                                   cfg.learning_rate)
            table = acc = None

            def step_fn(_t, _a, labels, weights, uniq_ids, local_idx, vals,
                        fields=None):
                loss, scores = offload_step(labels, weights, uniq_ids,
                                            local_idx, vals, fields)
                return None, None, loss, scores
        elif mesh is not None:
            if restored is not None:
                # The sharded template already placed these row-sharded on
                # this mesh in the runtime [ckpt_rows, D] layout — use as-is.
                table, acc = restored["table"], restored["acc"]
            else:
                table, acc = init_sharded_state(cfg, mesh, cfg.seed)
            step_fn = make_sharded_train_step(spec, mesh)
        else:
            if restored is not None:
                table = restored["table"][:cfg.num_rows]
                acc = restored["acc"][:cfg.num_rows]
                # The slices above are NEW device buffers; drop the full
                # [ckpt_rows, D] restored arrays so they free once the
                # slice completes — holding them for the whole run is a
                # sustained ~2x HBM cost that only bites on resume.
                restored["table"] = restored["acc"] = None
            else:
                table = init_table(cfg, cfg.seed)
                acc = init_accumulator(cfg)
            step_fn = make_train_step(spec)

        # Ownership ledger (obs/memory.py; README "Memory
        # observability"): the session's long-lived allocations
        # register with their owner tag so every flush carries mem/*
        # gauges and an OOM names which owner grew. .nbytes is host
        # metadata — no fetch. Offload state is host-resident by
        # construction (host=True: gauged, excluded from the device
        # live total). Released in this session's finally.
        if offload:
            LEDGER.register("offload_table",
                            table_bytes(rows=lk.rows, dim=lk.dim),
                            host=True)
            LEDGER.register("offload_acc",
                            table_bytes(rows=lk.rows, dim=lk.dim),
                            host=True)
        else:
            LEDGER.register("table", table.nbytes)
            LEDGER.register("adagrad_acc", acc.nbytes)

        # Wire format (README "Wire format"; wire.py): resolve the
        # knobs for THIS dispatch path, build the one encoder every
        # step ships through, and pre-build the packed step when
        # active. Staging (the explicit async device_put double
        # buffer) applies on the plain single-device jit path only —
        # mesh/lockstep placement and the offload host gather have
        # their own protocols.
        from fast_tffm_tpu.wire import WireEncoder, resolve_wire
        wire_spec = resolve_wire(cfg, mesh=mesh, backend=lk,
                                 multi_process=multi_process, train=True)
        wire_enc = WireEncoder(wire_spec, pad_id=cfg.pad_id)
        wire_stage = (not multi_process and mesh is None and not offload)
        packed_step = None
        if wire_spec.packed:
            from fast_tffm_tpu.models.fm import make_packed_train_step
            packed_step = make_packed_train_step(spec)
            logger.info(
                "wire format: %s (flat CSR + on-device unpack, "
                "double-buffered H2D)", wire_spec.describe())
        if tel is not None:
            # The active wire mode, as gauges — fmstat's transfer-bound
            # attribution names it beside the bytes-per-example row.
            tel.set("wire/packed", 1.0 if wire_spec.packed else 0.0)
            tel.set("wire/narrow", 1.0 if wire_spec.narrow else 0.0)

        # Step-anatomy join keys (obs/anatomy.py; README "Step
        # anatomy"): when on, the loops stamp the step id into the
        # h2d/step/flags spans (so fmtrace --anatomy can join phases
        # across ranks) and feed the host-side phase-seconds counters
        # the anatomy/* gauges aggregate at barrier flushes.
        anat = tel is not None and getattr(tel, "anatomy", False)

        def _wire_place(batch, step=0):
            """Encode one batch and place its arrays for dispatch —
            the ONE body both run-mode loops share (a drifted copy
            here is how the two modes' h2d accounting or placement
            would silently diverge). h2d_bytes = wb.wire_bytes sizes
            the arrays ACTUALLY shipped; the padded-layout size rides
            on wb.logical_bytes for the savings counter. ``step``
            (anatomy on) rides the h2d span as the cross-rank join
            key; the placed arms also feed the train/h2d_seconds
            anatomy phase counter."""
            wb = wire_enc.encode_train(batch)
            ids = {"step": step} if (anat and step) else {}
            t_h2d = time.perf_counter()
            placed = True
            if multi_process:
                # The global-array assembly ships every shard's bytes.
                with span("train/h2d", bytes=wb.wire_bytes, **ids):
                    args = global_batch(mesh, len(batch.uniq_ids),
                                        **wb.args)
            elif mesh is not None:
                with span("train/h2d", bytes=wb.wire_bytes, **ids):
                    args = shard_batch(mesh, **wb.args)
            elif wire_stage:
                # Depth-2 double buffer: the explicit async put rides
                # the copy stream while the PREVIOUS step is still
                # executing, instead of serializing at the head of
                # this step's dispatch.
                with span("train/h2d", bytes=wb.wire_bytes, **ids):
                    args = wire_enc.device_put(wb)
            else:
                args = wb.args
                placed = False
            if placed and tel is not None:
                tel.count("train/h2d_seconds",
                          time.perf_counter() - t_h2d)
            return wb, args

        def _wire_step(wb, args, table, acc):
            """Dispatch one placed batch through the right compiled
            step (shared by both loops, like _wire_place). Runs under
            oom_guard: a RESOURCE_EXHAUSTED here re-raises with the
            per-owner ledger attached (obs/memory.py)."""
            with oom_guard("train/step"):
                return _wire_step_inner(wb, args, table, acc)

        def _wire_step_inner(wb, args, table, acc):
            if multi_process:
                # The sharded step IS a collective program: on a dead
                # cluster its dispatch blocks inside the program's
                # collectives exactly like a host allgather (pinned by
                # the hang-worker chaos stack dumps), so it runs under
                # the same deadline guard. The dispatch wait is an
                # anatomy phase: jax dispatch is async (returns at
                # enqueue), so time spent HERE is queue backpressure —
                # the previous program still executing somewhere.
                from fast_tffm_tpu.parallel.liveness import (
                    guarded_collective)
                t_disp = time.perf_counter()
                out = guarded_collective(
                    step_fn, table, acc,
                    label="train/step_dispatch", **args)
                if tel is not None:
                    tel.count("train/dispatch_seconds",
                              time.perf_counter() - t_disp)
                return out
            if wb.packed:
                return packed_step(wb.L, table, acc, **args)
            return step_fn(table, acc, **args)

        def _vocab_reset(rows):
            """The eviction hook: cold-start freed rows through the
            backend's half of the slot seam (lookup.reset_rows for
            offload state, the fixed-width compiled scatter for
            device/mesh state — either way no per-count recompiles)."""
            nonlocal table, acc
            if offload:
                lk.reset_rows(rows, cfg.adagrad_init)
            else:
                from fast_tffm_tpu.vocab.table import reset_table_rows
                table, acc = reset_table_rows(table, acc, rows,
                                              cfg.pad_id,
                                              cfg.adagrad_init)

        def _vocab_barrier(where: str) -> None:
            if vocab is None:
                return
            st = vocab.barrier(_vocab_reset)
            logger.info(
                "vocab barrier (%s): +%d admitted, -%d evicted, %d/%d "
                "live rows", where, st["admitted"], st["evicted"],
                st["live"], cfg.vocabulary_size - 1)

        if vocab_fresh_over_restore:
            # Fresh admission over a restored table: every row —
            # including row 0, which becomes the shared COLD row but
            # held a fixed-mode mapping's trained embedding — still
            # carries the LOST mapping's weights. Cold-start them all
            # so neither the communal tail nor a newly admitted id
            # ever trains through another id's vector (the documented
            # row-owner invariant).
            _vocab_reset(np.arange(0, cfg.vocabulary_size,
                                   dtype=np.int32))
            logger.info(
                "cold-started %d table rows for the fresh admission "
                "state", cfg.vocabulary_size)

        # Per-publish quality loop + publish gate (README "SLOs &
        # quality gate"; obs/quality.py). When a stream run has a
        # validation corpus, every publish settle runs one validation
        # sweep — AUC/loss/calibration gauges ride the sweep's own
        # score fetches (zero added device traffic) — and the
        # configured gate decides whether the `published` pointer may
        # move. The helper is session-scoped (not inside _run_stream)
        # because the EXIT publish after the final save is gated too.
        from fast_tffm_tpu.obs.quality import PublishGate
        gate = PublishGate.from_config(cfg) if stream_mode else None
        # "auto" opts in exactly when the run declared a quality
        # objective (a gate knob, or slo_min_auc) — an existing stream
        # config with validation_files must not silently start paying
        # a validation sweep per publish on upgrade.
        _qmode = getattr(cfg, "publish_quality_eval", "auto")
        quality_on = (stream_mode and bool(cfg.validation_files)
                      and float(getattr(cfg, "publish_interval_seconds",
                                        0.0)) > 0
                      and (_qmode == "on"
                           or (_qmode == "auto"
                               and (gate is not None
                                    or getattr(cfg, "slo_min_auc",
                                               0.0) > 0))))
        if gate is not None:
            # The drop baseline survives restarts beside the pointer
            # (checkpoint.GATE_BASELINE): a preempt-resume must not
            # exempt its first publish from publish_max_auc_drop.
            from fast_tffm_tpu.checkpoint import read_gate_baseline
            gate.note_published(read_gate_baseline(ckpt.directory))
            logger.info(
                "publish gate armed: min AUC %s, max AUC drop %s%s "
                "(validation sweep at every publish settle)",
                cfg.publish_min_auc or "off",
                cfg.publish_max_auc_drop or "off",
                "" if gate.baseline is None
                else f", restored baseline {gate.baseline:.6f}")

        def _gate_published(decision) -> None:
            """Advance (and persist) the drop baseline after a publish
            actually landed — the one baseline-write path for both the
            interval publishes and the exit publish."""
            if gate is None or decision is None:
                return
            gate.note_published(decision.get("auc"))
            if gate.baseline is not None and jax.process_index() == 0:
                from fast_tffm_tpu.checkpoint import write_gate_baseline
                write_gate_baseline(ckpt.directory, gate.baseline)

        def _publish_decision() -> Optional[dict]:
            """Quality sweep + gate decision for the publish about to
            happen; None when no quality loop is configured (publish
            unconditionally). Rides the publish settle point the
            caller already synchronized at; multi-host safe: the sweep
            merge is collective and the chief's decision is broadcast
            (obs/quality.PublishGate docstring), so all workers skip
            or run the save/publish below together."""
            if not quality_on:
                return None
            from fast_tffm_tpu.obs.quality import (QualityStats,
                                                   emit_gate_held,
                                                   emit_quality)
            stats = QualityStats(cfg.loss_type)
            vmb = cfg.validation_max_batches or None
            # fmlint: disable=R003 -- feeds the quality/eval_seconds
            # counter (the quality/eval span is the timeline view)
            t_q = time.perf_counter()
            with span("quality/eval", step=global_step):
                if multi_process:
                    # preempt rides the lockstep window allgather like
                    # every other multi-process sweep: a SIGTERM mid-
                    # sweep stops ALL workers at the same window
                    # boundary instead of finishing the full
                    # validation pass inside the kill grace window.
                    auc, n = evaluate_distributed(
                        cfg, table, cfg.validation_files, mesh,
                        shard_index, num_shards,
                        uniq_bucket=val_bucket, max_batches=vmb,
                        weight_files=cfg.validation_weight_files,
                        bad_lines=bad_tracker, collect=stats,
                        preempt=lambda: bool(preempted))
                else:
                    auc, n = evaluate(
                        cfg, table, cfg.validation_files, mesh=mesh,
                        backend=lk, max_batches=vmb,
                        weight_files=cfg.validation_weight_files,
                        bad_lines=bad_tracker, vocab=vocab,
                        collect=stats)
            # fmlint: disable=R003 -- closes the eval-cost sample
            dt_q = time.perf_counter() - t_q
            if jax.process_index() == 0:
                # Chief-only: n and the merged stats are already
                # job-global, and per-worker shard counters merge by
                # SUM in fmstat — every worker emitting would inflate
                # quality/evals and quality/examples by P.
                emit_quality(tel, global_step, float(auc), stats, n,
                             dt_q)
            if tel is not None:
                tel.heartbeat()  # a long sweep is progress, not a stall
            if jax.process_index() == 0:
                logger.info(
                    "publish quality eval at step %d: AUC %.6f, loss "
                    "%s, calibration %s over %d examples (%.2fs)",
                    global_step, auc,
                    "-" if stats.loss is None
                    else f"{stats.loss:.6f}",
                    "-" if stats.calibration is None
                    else f"{stats.calibration:.4f}", n, dt_q)
            if gate is None:
                return {"held": False, "auc": float(auc),
                        "examples": int(n)}
            # Chief decides, broadcast: identity single-process; every
            # worker applies the byte-identical decision.
            from fast_tffm_tpu.data.stream import broadcast_blob
            decision = broadcast_blob(
                gate.decide(float(auc), global_step),
                "quality/gate_decision")
            # n is already job-global (the sweep merge), so adding it
            # after the broadcast stays identical on every worker.
            decision["examples"] = int(n)
            if decision["held"]:
                if jax.process_index() == 0:
                    # Chief-only, like emit_quality: one hold must
                    # count once, not once per worker shard.
                    emit_gate_held(tel, decision)
                logger.warning(
                    "publish GATE HELD at step %d: %s — the published "
                    "pointer stays on the last passing step",
                    global_step, "; ".join(decision["reasons"]))
            return decision

        # Preemption handling (SURVEY §5 "Failure detection": the reference
        # only recovers via restart+restore; we additionally save on the way
        # down). SIGTERM/SIGINT sets a flag the loop drains at the next step
        # boundary — in multi-process mode the flag rides the lockstep
        # allgather so every process saves/exits together even when only one
        # received the signal.
        preempted: list = []
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev_handlers[sig] = signal.signal(
                    sig, lambda s, f: preempted.append(s))
            except ValueError:  # not the main thread (e.g. under a test)
                pass

        run_start_step = global_step  # profile window counts THIS run's steps
        # (a resumed job would otherwise skip past the window silently)

        def profile_tick(step_done: int) -> None:
            nonlocal profiling
            if not cfg.profile_dir or jax.process_index() != 0:
                return
            step_done -= run_start_step
            if (not profiling and step_done >= cfg.profile_start_step
                    and step_done < cfg.profile_start_step
                    + cfg.profile_num_steps):
                jax.profiler.start_trace(cfg.profile_dir)
                profiling = True
            elif profiling and step_done >= (cfg.profile_start_step
                                             + cfg.profile_num_steps):
                if table is not None:
                    jax.block_until_ready(table)
                jax.profiler.stop_trace()
                profiling = False
                logger.info("profiler trace written to %s", cfg.profile_dir)

        timer = StepTimer()
        loss = None
        loss_val = float("nan")
        stopping = False
        last_val = None  # (auc, n) of the most recent validation pass


        # TensorBoard scalars (save_summaries_steps; utils/summaries.py).
        # Chief-only, and flushed ONLY at epoch barriers: values buffer as
        # device scalars so the cadence adds zero mid-stream fetches.
        if cfg.save_summaries_steps and jax.process_index() == 0:
            from fast_tffm_tpu.utils.summaries import make_summaries
            summaries = make_summaries(cfg)
            if summaries is not None:
                logger.info("writing TensorBoard summaries every %d steps "
                            "to %s", cfg.save_summaries_steps,
                            summaries.logdir)

        # Adaptive loss logging. float(loss) is a synchronous device->host
        # fetch; on direct-attached devices it costs microseconds, but over
        # a proxied/tunnelled device link ANY mid-stream fetch stalls the
        # async dispatch pipeline catastrophically (measured here: ONE
        # scalar fetch in a hot stream costs seconds, 528k -> 50k
        # examples/sec even at a 1/25-step cadence; copy_to_host_async is
        # just as bad). So the first log step measures the fetch once: if
        # it is cheap, logging stays live (the normal-hardware behavior);
        # if not, loss values are buffered ON DEVICE (scalars) and flushed
        # at epoch boundaries — a natural barrier — with correct per-step
        # attribution.
        # Probe the link BEFORE the hot loop, with an empty dispatch queue:
        # a mid-stream probe on a slow link costs seconds (it drains the
        # queue through the slow path — measured ~10 s at step 61 of a
        # criteo-shaped run) where this costs one clean round-trip.
        def _probe_link() -> str:
            import time as _time
            if cfg.log_steps <= 0:
                return "deferred"  # mode never consulted without log lines
            # fmlint: disable=R013 -- a one-scalar link-latency probe,
            # not a batch: the wire encoder has nothing to encode here
            probe = jax.device_put(np.float32(0.0))
            jax.block_until_ready(probe)
            float(probe)  # throwaway: lazy transfer-path init stays untimed
            cost = float("inf")
            for _ in range(3):  # min of 3: jitter must not misclassify
                # fmlint: disable=R003 -- this IS the link probe's
                # deliberate timer, before the hot loop starts
                t0 = _time.perf_counter()
                # fmlint: disable=R001 -- this IS the link probe: one
                # deliberate timed scalar fetch, before the hot loop starts
                float(probe)
                # fmlint: disable=R003 -- closes the probe sample
                cost = min(cost, _time.perf_counter() - t0)
            if cost < LIVE_FETCH_BUDGET_S:
                # Log the decision either way: a user wondering why loss
                # lines are (or aren't) live gets the probe's answer.
                logger.info("scalar fetch costs %.3f ms on this device link; "
                            "loss log lines stay live", cost * 1e3)
                return "live"
            logger.info(
                "scalar fetch costs %.0f ms on this device link; deferring "
                "loss log lines to epoch boundaries to keep the dispatch "
                "pipeline hot", cost * 1e3)
            return "deferred"

        log_mode = _probe_link()
        log_buffer: list = []    # deferred: (step, epoch, loss_arr, eps)

        def log_line(s, ep, val, eps):
            nonlocal loss_val
            loss_val = val
            logger.info("step %d epoch %d loss %.6f examples/sec %.0f",
                        s, ep, val, eps)

        def log_tick(s, ep, loss_arr, eps):
            if log_mode == "deferred":
                log_buffer.append((s, ep, loss_arr, eps))
                # Bound the buffer: log_steps=1 on a months-long epoch must
                # not retain unbounded device scalars; one rare mid-epoch
                # sync is the lesser evil.
                if len(log_buffer) >= LOG_BUFFER_MAX:
                    flush_log()
                return
            log_line(s, ep, float(loss_arr), eps)

        def flush_log():
            if not log_buffer:
                return
            # bulk_fetch stacks the same-shaped scalars into ONE transfer:
            # deferred mode is only ever active on a slow device link,
            # where a per-element list fetch costs ~200 ms EACH
            # (utils/fetch.py) — a full 1024-entry buffer would stall for
            # minutes.
            bulk_fetch([(arr, (s, ep, eps))
                        for s, ep, arr, eps in log_buffer],
                       lambda v, m: log_line(m[0], m[1], float(v), m[2]))
            log_buffer.clear()
        # Handlers stay installed (absorbing re-signals) until the finally
        # below — i.e. until the final checkpoint/export is safely on disk,
        # the window a second SIGTERM is most likely to arrive in. The
        # finally also covers exceptions, so a failed in-process train()
        # can't leave the surviving process (pytest, REPL, server) with
        # SIGTERM/SIGINT swallowed into a dead flag list.
        completed_epochs = start_epoch
        last_periodic_save = (None, None)  # (step, epoch) of the latest
        # Streaming run mode (README "Streaming / online learning"):
        # the durable stream position adopted from STEPPED batches —
        # what every checkpoint records beside the arrays, so restore
        # resumes with no example duplicated or skipped. None in epoch
        # mode (saves then carry no watermark sidecar).
        stream_watermark = None

        def _stream_state_for_save():
            """The watermark payload a save should carry right now:
            merged across workers at this lockstep point (a collective
            when multi-process — callers must invoke it at
            step-deterministic points only)."""
            if not stream_mode:
                return None
            from fast_tffm_tpu.data.stream import exchange_watermarks
            wm = stream_watermark or {"format": 1, "files": []}
            return (exchange_watermarks(wm, num_shards)
                    if multi_process else wm)

        def _run_stream():
            """The indefinitely-surviving online loop: poll the stream
            source, step every arriving batch, save with the watermark,
            and publish a manifest-verified checkpoint every
            ``publish_interval_seconds``. Single-process overlaps build
            and compute through the prefetch thread; multi-worker runs
            the source inline on this thread so its one discovery
            collective per iteration stays aligned with the lockstep
            flags allgather and the step program (collectives from two
            threads would interleave nondeterministically across
            workers — the deadlock class the window protocol exists to
            prevent)."""
            nonlocal global_step, loss, stopping, stream_watermark, \
                last_periodic_save, table, acc
            from fast_tffm_tpu.data import stream as streamlib
            from fast_tffm_tpu.data.pipeline import empty_batch
            restored_wm = (restored or {}).get("stream")
            # Seed the adopted position from the restored sidecar: a
            # recovered session (elastic shrink/grow, preempt-resume)
            # saves at its restored step BEFORE any new batch steps —
            # publish settles fire on idle ticks — and an empty
            # in-memory watermark there would REWRITE the step's
            # sidecar to empty, wiping the durable position and
            # double-training the whole consumed prefix after the
            # next restore (caught by the kill-then-grow soak).
            stream_watermark = restored_wm
            if restored is not None and restored_wm is None:
                logger.warning(
                    "restored checkpoint at step %d carries no stream "
                    "watermark (an epoch-mode warm start, or a lost "
                    "watermark sidecar): streaming starts from the "
                    "BEGINNING of %s — any stream bytes this model "
                    "already trained on will be trained again",
                    global_step, cfg.stream_dir)
            tracker = streamlib.StreamTracker(
                cfg.stream_dir, cfg.stream_poll_seconds,
                cfg.seal_policy, retry=RetryPolicy.from_config(cfg),
                shard_index=shard_index, num_shards=num_shards,
                bad_lines=bad_tracker, watermark=restored_wm,
                lockstep=multi_process)
            u_bucket = 0
            if multi_process:
                u_bucket = (cfg.uniq_bucket
                            or streamlib.probe_stream_uniq_bucket(
                                cfg, tracker))
                logger.info("fixed unique-row bucket: %d", u_bucket)
            workers = streamlib.stream_workers(
                cfg, fixed_shape=multi_process)
            if workers > 1:
                logger.info(
                    "stream host data plane: %d parallel batch-build "
                    "workers (host_threads = %s; sealed line groups "
                    "through the bounded ordered ring)",
                    workers, cfg.host_threads)
            source = streamlib.StreamSource(
                cfg, tracker,
                stop=(None if multi_process
                      else (lambda: bool(preempted))),
                fixed_shape=multi_process, uniq_bucket=u_bucket,
                raw_ids=raw_mode, workers=workers,
                bad_lines=bad_tracker, vocab=vocab)
            publish_every = float(
                getattr(cfg, "publish_interval_seconds", 0.0))
            last_publish = [time.monotonic()]
            # The freshness gauge (and the STALE PUBLISH verdict) track
            # the last SUCCESSFUL publish, separately from the attempt
            # clock above: a gate that keeps holding advances the
            # cadence but NOT the pointer — the age must keep growing
            # so a long hold surfaces as STALE PUBLISH, the closed
            # loop's designed failure signal.
            last_publish_ok = [time.monotonic()]
            # Whether the LAST gate decision held. While holding, the
            # retention-pressure publish trigger below is disarmed: a
            # republish attempt cannot succeed (the gate would hold
            # the same regressed state again), so re-arming it would
            # spin a full validation sweep per loop iteration for the
            # whole hold. The interval arm keeps re-evaluating at the
            # publish cadence — the bounded re-check that notices
            # recovery.
            gate_holding = [False]
            # One retention-pause log per hold episode (see step_once).
            risk_pause_logged = [False]
            if tel is not None:
                tel.set("stream/publish_interval_seconds",
                        publish_every)

            def publish_due() -> bool:
                """Interval elapsed, OR retention pressure: periodic
                save_steps saves must never GC the published step out
                from under a scorer mid-interval — republishing first
                repoints at fresh state instead of letting the pointer
                dangle. Chief-only in lockstep mode (the decision
                rides the flags allgather)."""
                if publish_every <= 0:
                    return False
                if time.monotonic() - last_publish[0] >= publish_every:
                    return True
                # Gated runs check one retention slot EARLY (margin=2):
                # the very tick this arm triggers may turn out HELD,
                # and a hold starting at the margin-1 boundary would
                # leave the mandatory final/preemption save to evict
                # the last-good step — the reserve the save pause
                # depends on must exist BEFORE the hold begins.
                return (bool(cfg.save_steps) and not gate_holding[0]
                        and ckpt.published_at_risk(
                            margin=2 if gate is not None else 1))

            def stream_gauges():
                if tel is None:
                    return
                tel.set("stream/watermark_lag_seconds",
                        tracker.watermark_lag_seconds())
                if publish_every > 0:
                    tel.set("stream/last_publish_age_seconds",
                            time.monotonic() - last_publish_ok[0])

            def stream_save(wait: bool, force: bool = False) -> None:
                nonlocal last_periodic_save
                state = (lk.state() if offload
                         else ckpt_state(cfg, table, acc))
                ckpt.save(global_step, *state,
                          vocabulary_size=cfg.vocabulary_size,
                          force=force, wait=wait, epoch=0,
                          stream_state=_stream_state_for_save(),
                          vocab_state=(vocab.state_payload()
                                       if vocab is not None else None))
                last_periodic_save = (global_step, 0)
                if tel is not None:
                    tel.count("train/checkpoints")

            def do_publish() -> None:
                """Quality eval + gate, then save + settle the
                manifest + verify + atomically repoint the
                ``published`` pointer. A HELD decision skips the save
                too: a held tick must not mint a new step — retention
                (max_to_keep) could otherwise use held steps to lap
                the published pointer, deleting the exact "last good
                triple" the gate exists to keep serving. Lockstep-safe:
                the decision is chief-broadcast, so every worker runs
                the save's commit barrier (or skips it) together; only
                process 0 flips the pointer."""
                with span("checkpoint/publish", step=global_step):
                    # fmlint: disable=R003 -- feeds the train/
                    # checkpoint_pause_seconds counter (the publish
                    # span is the timeline view)
                    t_pub = time.perf_counter()
                    # Publish settle IS a vocab barrier point: the
                    # published (table, slot map, step) triple a
                    # scorer hot-reloads must be post-admission/
                    # eviction coherent — evicted rows reset BEFORE
                    # the save, so the published step serves evicted
                    # ids from the cold row, never stale embeddings.
                    # (It runs before the quality eval, so the sweep
                    # measures exactly the state a pass would publish.)
                    _vocab_barrier(f"publish step {global_step}")
                    decision = _publish_decision()
                    gate_holding[0] = bool(decision
                                           and decision.get("held"))
                    if not gate_holding[0]:
                        risk_pause_logged[0] = False
                    if decision is None or not decision.get("held"):
                        # force=True: a publish can land on the SAME
                        # step as the last periodic save, and the
                        # barrier above just moved the in-memory
                        # (table, slot map) pair — the benign same-
                        # step-collision skip would pair the old
                        # arrays with the new sidecar. Forcing
                        # rewrites both, so the published triple is
                        # coherent.
                        stream_save(wait=True, force=vocab is not None)
                        ok = ckpt.publish_step(global_step) is not None
                        # Non-chief workers assume the chief's verify
                        # passed (publish_step is process-0-only; a
                        # verify failure is already counted and the
                        # decision stream stays chief-broadcast, so a
                        # rare divergent baseline here cannot diverge
                        # an outcome).
                        if ok or jax.process_index() != 0:
                            last_publish_ok[0] = time.monotonic()
                            _gate_published(decision)
                    # held: no save, no publish — and once the
                    # published step reaches the retention boundary,
                    # step_once pauses periodic saves too, so GC can
                    # never evict the last-good checkpoint mid-hold.
                    if tel is not None:
                        # fmlint: disable=R003 -- closes the sample
                        tel.count("train/checkpoint_pause_seconds",
                                  time.perf_counter() - t_pub)
                last_publish[0] = time.monotonic()
                stream_gauges()
                if grow_ctx is not None and not gate_holding[0]:
                    # The publish settle IS the grow barrier in stream
                    # mode (the same sync point the vocab barrier
                    # rides): the save above just landed with the
                    # merged watermark (wait=True), so a newcomer's
                    # verified restore resumes the stream exactly-once
                    # from this point. A HELD publish skipped the save
                    # — no durable barrier state, no admission; the
                    # chief-broadcast hold decision keeps every worker
                    # on the same arm.
                    plan = grow_ctx.check_barrier()
                    if plan is not None:
                        raise ClusterGrowth(plan)

            # fmlint: disable=R003 -- anchors the stream step-seconds
            # window (always-on aggregate)
            t_prev = [time.perf_counter()]

            def step_once(batch) -> None:
                nonlocal global_step, loss, stream_watermark
                nonlocal table, acc
                if vocab is not None:
                    # A publish barrier may have moved the slot map
                    # while this batch sat in the prefetch queue —
                    # redo its remap so it never scatters into rows
                    # the barrier evicted/reset/reassigned (one int
                    # compare when nothing moved).
                    batch = vocab.ensure_current(batch)
                wb, args = _wire_place(batch, global_step + 1)
                h2d_bytes = wb.wire_bytes
                with span("train/step", step=global_step + 1):
                    table, acc, loss, _ = _wire_step(wb, args,
                                                     table, acc)
                global_step += 1
                if batch.stream_pos is not None:
                    # The durable position advances ONLY with stepped
                    # batches (lockstep fillers carry None).
                    stream_watermark = batch.stream_pos
                if vocab is not None:
                    # Adopt-on-step, like the watermark: the sketch
                    # advances only for trained batches, so the
                    # checkpointed admission state and the stream
                    # position describe the same prefix.
                    vocab.note_trained(batch)
                # Log-line rate: the job-global estimate (x P assumes
                # symmetric shards — exact under line sharding, an
                # estimate under whole-file stream ownership). The
                # COUNTER is this worker's OWN real examples: shard
                # files merge by sum, so anything else would inflate
                # the exactly-once accounting P-fold (and whole-file
                # ownership pays fillers as phantom examples).
                n_global = batch.num_real * (jax.process_count()
                                             if multi_process else 1)
                timer.tick(n_global)
                if tel is not None:
                    # fmlint: disable=R003 -- feeds the train/
                    # step_seconds histogram (always-on aggregate)
                    now = time.perf_counter()
                    tel.train_step(now - t_prev[0], batch.num_real,
                                   h2d_bytes, wb.logical_bytes)
                    t_prev[0] = now
                    tel.heartbeat(global_step)
                profile_tick(global_step)
                log_due = (cfg.log_steps
                           and global_step % cfg.log_steps == 0)
                tel_due = (tel is not None
                           and tel.flush_due(global_step))
                eps_now = (timer.consume_window_rate()
                           if (log_due or tel_due) else None)
                if log_due:
                    log_tick(global_step, 0, loss, eps_now)
                if tel_due:
                    tel.add_scalar("train/loss", global_step, loss)
                    tel.set("train/examples_per_sec_window", eps_now)
                    tel.set("train/examples_per_sec_total",
                            timer.total_examples_per_sec)
                    stream_gauges()
                    tel.maybe_flush(global_step)
                if cfg.save_steps and global_step % cfg.save_steps == 0:
                    # margin=2: stop one slot shy of the boundary so
                    # the mandatory final/preemption save can still
                    # land without evicting the last-good step.
                    if (gate_holding[0]
                            and ckpt.published_at_risk(margin=2)):
                        # Retention pause: while the gate is HOLDING,
                        # a periodic save that would push the
                        # published (last-good) step past max_to_keep
                        # must not run — orbax's newest-N eviction has
                        # no pin, so minting the step would delete the
                        # exact checkpoint the fleet is serving from
                        # (published_at_risk's "the pointer never
                        # names a deleted step" contract). Durability
                        # pauses for the hold — progress since the
                        # last save is re-trained on a crash, exactly
                        # once via the watermark — and resumes when
                        # the gate passes (the publish repoints at
                        # fresh state, clearing the risk).
                        if not risk_pause_logged[0]:
                            risk_pause_logged[0] = True
                            logger.warning(
                                "publish gate holding with the "
                                "published step at the retention "
                                "boundary: pausing periodic saves so "
                                "GC cannot evict the last-good "
                                "checkpoint; heal the input stream "
                                "(or raise max_to_keep) to resume")
                    else:
                        # fmlint: disable=R003 -- feeds the train/
                        # checkpoint_pause_seconds counter
                        t_ck = time.perf_counter()
                        # Gated runs save SYNCHRONOUSLY: the retention
                        # math protecting the published step (the
                        # margin=2 risk arm + the hold pause above)
                        # reasons over COMMITTED step dirs — an async
                        # save's invisible in-flight step would let a
                        # hold latch with the window already full, and
                        # the mandatory final save would then evict
                        # the exact last-good checkpoint the gate
                        # pinned (caught by the retention-pause e2e
                        # test).
                        stream_save(wait=offload or gate is not None)
                        if tel is not None:
                            # fmlint: disable=R003 -- closes the sample
                            dt_ck = time.perf_counter() - t_ck
                            tel.count("train/checkpoint_pause_seconds",
                                      dt_ck)
                            t_prev[0] += dt_ck

            def emit_preempted() -> None:
                nonlocal stopping
                stopping = True
                logger.info("preemption signalled; saving the stream "
                            "position and exiting")
                if tel is not None:
                    tel.sink.emit("health", {
                        "status": "preempted", "step": global_step,
                        "epoch": 0})

            try:
                if multi_process:
                    from jax.experimental import multihost_utils
                    from fast_tffm_tpu.parallel.liveness import (
                        guarded_collective)
                    while True:
                        b = source.next_batch(block=False)
                        has = b not in (streamlib.IDLE, streamlib.DONE)
                        done = b is streamlib.DONE
                        pub_due = publish_due()
                        # The flags allgather is the stream loop's
                        # rank barrier: time parked here is waiting
                        # for the slowest peer (anatomy flags-wait
                        # phase; the span's step id is the cross-rank
                        # join key).
                        ids = ({"step": global_step + 1} if anat
                               else {})
                        # fmlint: disable=R003 -- feeds the train/
                        # step_flags_seconds anatomy counter
                        t_fl = time.perf_counter()
                        with span("stream/step_flags", **ids):
                            flags = np.asarray(guarded_collective(
                                multihost_utils.process_allgather,
                                np.asarray([has, bool(preempted),
                                            done, pub_due]),
                                label="stream/step_flags"
                                )).reshape(-1, 4)
                        if tel is not None:
                            # fmlint: disable=R003 -- closes the
                            # flags-wait sample
                            tel.count("train/step_flags_seconds",
                                      time.perf_counter() - t_fl)
                        if bool(flags[:, 1].any()):
                            emit_preempted()
                            break
                        if bool(flags[:, 2].all()) and not bool(
                                flags[:, 0].any()):
                            break
                        if bool(flags[:, 0].any()):
                            batch = (b if has else empty_batch(
                                cfg, uniq_bucket=u_bucket))
                            step_once(batch)
                        else:
                            if tel is not None:
                                tel.heartbeat()
                            stream_gauges()
                            time.sleep(min(cfg.stream_poll_seconds,
                                           0.5))
                        if bool(flags[0, 3]):  # the CHIEF's clock
                            do_publish()
                else:
                    # StreamPrefetcher, not pipeline.prefetch: the
                    # driver must keep its publish clock and
                    # preemption checks ticking while the stream
                    # idles — a blocking queue get would starve
                    # publishing for as long as no batch arrives.
                    pf = streamlib.StreamPrefetcher(
                        source, depth=cfg.prefetch_depth)
                    try:
                        while True:
                            if preempted:
                                emit_preempted()
                                break
                            batch = pf.get(timeout=min(
                                cfg.stream_poll_seconds, 0.5))
                            # fmlint: disable=R007 -- single-process
                            # arm (the lockstep arm above is the
                            # multi-worker path): step_once's
                            # collectives are themselves gated on
                            # multi_process, so no peer exists to
                            # diverge from; `batch` reads as
                            # rank-tainted only through the tracker's
                            # shard_index plumbing
                            # fmlint: disable=R014 -- same
                            # single-process-arm justification: the
                            # loop's collectives are all gated on
                            # multi_process, so this escape leaves no
                            # peer's sequence unmatched
                            if batch is streamlib.DONE:
                                if preempted:
                                    emit_preempted()
                                break
                            # fmlint: disable=R007 -- same
                            # single-process-arm justification as above
                            if batch is streamlib.IDLE:
                                if tel is not None:
                                    tel.heartbeat()
                                stream_gauges()
                            else:
                                step_once(batch)
                            if publish_due():
                                do_publish()
                    finally:
                        pf.close()
            finally:
                source.close()
            stream_gauges()  # the exit metrics snapshot carries the
            # freshness gauges even when the run never hit a flush step
            flush_log()
            if bad_tracker is not None and bad_tracker.bad:
                logger.info("bad-line policy through the stream run: "
                            "%s", bad_tracker.describe())
            if source.stats.batches:
                logger.info("stream input: %s",
                            source.stats.describe())

        if stream_mode:
            _run_stream()
            epoch_schedule = range(0)  # the epoch loop never runs
        else:
            epoch_schedule = range(start_epoch, cfg.epoch_num)
        for epoch in epoch_schedule:
            if stopping:
                break
            epoch_stats = SpillStats()
            it = prefetch(batch_iterator(
                cfg, cfg.train_files, training=True,
                weight_files=cfg.weight_files, shard_index=shard_index,
                num_shards=num_shards, epochs=1, seed=cfg.seed + epoch,
                fixed_shape=multi_process, uniq_bucket=uniq_bucket,
                stats=epoch_stats, raw_ids=raw_mode,
                bad_lines=bad_tracker, vocab=vocab),
                depth=cfg.prefetch_depth,
                gil_bound=gil_bound_iteration(cfg, cfg.weight_files))
            # fmlint: disable=R003 -- anchors the per-epoch
            # step-seconds window (always-on aggregate)
            t_step_prev = time.perf_counter()
            while True:
                # Consumer-side stall: time blocked INSIDE next() only —
                # bracketing it any wider would fold end-of-step
                # bookkeeping (notably live-mode's deliberate
                # float(loss) device sync in log_tick) into the
                # host-bound signal and misdiagnose a device-bound run
                # (the producer-side build cost is timed separately in
                # pipeline.batch_iterator on the worker thread).
                # fmlint: disable=R003 -- feeds the train/
                # input_wait_seconds counter (always-on aggregate)
                t_in = time.perf_counter() if tel is not None else 0.0
                batch = next(it, None)
                if tel is not None:
                    # fmlint: disable=R003 -- closes the input-wait sample
                    tel.count("train/input_wait_seconds",
                              time.perf_counter() - t_in)
                if multi_process:
                    # Lockstep: line-index sharding can give processes
                    # batch counts differing by one; every step is a
                    # collective program, so a process that stepped alone
                    # would hang the cluster. Agree on exhaustion/
                    # preemption each step (tiny host allgather) and feed
                    # all-padding filler batches (zero weight -> zero
                    # loss/grad) until everyone is done. The deadline
                    # guard bounds the wait: a dead peer raises
                    # WorkerLostError naming it instead of parking the
                    # survivors here forever (parallel/liveness.py).
                    from jax.experimental import multihost_utils
                    from fast_tffm_tpu.parallel.liveness import (
                        guarded_collective)
                    # The epoch loop's rank barrier (anatomy flags-
                    # wait phase; span step id = cross-rank join key).
                    # On CPU+gloo this wait also absorbs the PREVIOUS
                    # step's still-executing program — allgather
                    # blocks behind queued device work — which is
                    # exactly what the anatomy report names.
                    ids = {"step": global_step + 1} if anat else {}
                    # fmlint: disable=R003 -- feeds the train/
                    # step_flags_seconds anatomy counter
                    t_fl = time.perf_counter()
                    with span("train/step_flags", **ids):
                        flags = guarded_collective(
                            multihost_utils.process_allgather,
                            np.asarray([batch is None,
                                        bool(preempted)]),
                            label="train/step_flags")
                    if tel is not None:
                        # fmlint: disable=R003 -- closes the flags-
                        # wait sample
                        tel.count("train/step_flags_seconds",
                                  time.perf_counter() - t_fl)
                    if bool(flags[..., 1].any()):
                        stopping = True
                        logger.info(
                            "preemption signalled; saving and exiting")
                        if tel is not None:
                            # Distinct health event: fmstat must report
                            # a clean preemption exit as PREEMPTED, not
                            # conflate it with a crash (obs/attribution
                            # health_verdict).
                            tel.sink.emit("health", {
                                "status": "preempted",
                                "step": global_step, "epoch": epoch})
                        break
                    if bool(flags[..., 0].all()):
                        break
                    if batch is None:
                        from fast_tffm_tpu.data.pipeline import empty_batch
                        batch = empty_batch(cfg, uniq_bucket=uniq_bucket)
                else:
                    if preempted:
                        stopping = True
                        logger.info(
                            "preemption signalled; saving and exiting")
                        if tel is not None:
                            # fmlint: disable=R001 -- preempted holds
                            # host signal numbers from the handler,
                            # never device arrays
                            sigs = [int(s) for s in preempted]
                            tel.sink.emit("health", {
                                "status": "preempted",
                                "step": global_step, "epoch": epoch,
                                "signals": sigs})
                        break
                    # fmlint: disable=R014 -- single-process arm (the
                    # multi_process arm above agrees on exhaustion via
                    # the train/step_flags allgather before breaking);
                    # the loop's collectives are gated on multi_process
                    # so this escape leaves no peer unmatched
                    if batch is None:
                        break
                if vocab is not None:
                    # Epoch barriers only run once the epoch's iterator
                    # is exhausted, so nothing should be stale here —
                    # this is the one-integer-compare insurance the
                    # stream loop actually needs (see step_once).
                    batch = vocab.ensure_current(batch)
                wb, args = _wire_place(batch, global_step + 1)
                h2d_bytes = wb.wire_bytes
                # trace_span only while a profiler window is open: a
                # per-step TraceAnnotation costs ~14x throughput on this
                # platform when nothing is tracing. (Distinct from the
                # obs/trace JSONL span around it: that one is a no-op
                # unless the run enabled trace_spans.)
                prof_ann = (trace_span("train_step") if profiling
                            else contextlib.nullcontext())
                with span("train/step", step=global_step + 1):
                    with prof_ann:
                        table, acc, loss, _ = _wire_step(wb, args,
                                                         table, acc)
                global_step += 1
                last_val = None  # table advanced; any cached AUC is stale
                if vocab is not None:
                    vocab.note_trained(batch)  # adopt-on-step: only
                    # TRAINED batches feed the admission sketch
                # Counter = LOCAL real examples (shard files merge by
                # sum — see the stream loop's note); n_global feeds
                # only the log-line rate estimate.
                n_global = batch.num_real * (jax.process_count()
                                             if multi_process else 1)
                timer.tick(n_global)
                if tel is not None:
                    # Wall time since the previous step's bookkeeping —
                    # dispatch-loop time, never a device sync. Reset per
                    # epoch so validation/pause gaps stay out of the
                    # histogram (they have their own counters).
                    # fmlint: disable=R003 -- feeds the train/
                    # step_seconds histogram (always-on aggregate; the
                    # train/step span is the timeline view)
                    now = time.perf_counter()
                    tel.train_step(now - t_step_prev, batch.num_real,
                                   h2d_bytes, wb.logical_bytes)
                    t_step_prev = now
                    # Watchdog progress beat: one tuple assignment
                    # (obs/health.py) — the stall detector's only
                    # hot-path cost.
                    tel.heartbeat(global_step)
                profile_tick(global_step)
                log_due = (cfg.log_steps
                           and global_step % cfg.log_steps == 0)
                sum_due = (summaries is not None and global_step
                           % cfg.save_summaries_steps == 0)
                tel_due = tel is not None and tel.flush_due(global_step)
                # One windowed-rate read per step: the read consumes
                # the window, so the log line, the summary, and the
                # metrics gauge all share it.
                eps_now = (timer.consume_window_rate()
                           if (log_due or sum_due or tel_due) else None)
                if log_due:
                    log_tick(global_step, epoch, loss, eps_now)
                if sum_due:
                    summaries.add("train/loss", global_step, loss)
                    summaries.add("train/examples_per_sec", global_step,
                                  eps_now)
                if tel_due:
                    # loss is a DEVICE scalar: buffered, fetched only at
                    # the next epoch barrier (sink link-safety contract).
                    tel.add_scalar("train/loss", global_step, loss)
                    tel.set("train/examples_per_sec_window", eps_now)
                    tel.set("train/examples_per_sec_total",
                            timer.total_examples_per_sec)
                    tel.maybe_flush(global_step)  # file I/O only
                if cfg.save_steps and global_step % cfg.save_steps == 0:
                    # fmlint: disable=R003 -- feeds the train/
                    # checkpoint_pause_seconds counter (the
                    # checkpoint/save span is the timeline view)
                    t_ck = time.perf_counter()
                    state = (lk.state() if offload
                             else ckpt_state(cfg, table, acc))
                    # Device arrays: async save (orbax D2H-snapshots
                    # synchronously, writes in background — the loop
                    # doesn't stall for serialization). Host-offload
                    # state: wait, because the background writer would
                    # race the in-place numpy Adagrad updates.
                    ckpt.save(global_step, *state,
                              vocabulary_size=cfg.vocabulary_size,
                              wait=offload, epoch=completed_epochs,
                              vocab_state=(vocab.state_payload()
                                           if vocab is not None
                                           else None))
                    last_periodic_save = (global_step, completed_epochs)
                    if tel is not None:
                        # fmlint: disable=R003 -- closes the pause sample
                        dt_ck = time.perf_counter() - t_ck
                        tel.count("train/checkpoint_pause_seconds",
                                  dt_ck)
                        tel.count("train/checkpoints")
                        t_step_prev += dt_ck  # keep the pause out of
                        # the next step's step_seconds sample
            flush_log()  # deferred loss lines land at the epoch barrier
            if bad_tracker is not None and bad_tracker.bad:
                # Cumulative run-level view: the breaker and quarantine
                # are run-scoped, so the log line is too.
                logger.info("bad-line policy through epoch %d: %s",
                            epoch, bad_tracker.describe())
            if epoch_stats.spilled_batches or (multi_process
                                               and epoch_stats.batches):
                # Spill visibility (fixed-U mode): a probe-missed dense
                # stretch degrades fill silently otherwise.
                logger.info("epoch %d input: %s", epoch,
                            epoch_stats.describe())
                if epoch_stats.spill_fraction > SPILL_WARN_FRACTION:
                    logger.warning(
                        "uniq_bucket %d is undersized for this data: "
                        "%.0f%% of batches closed early on the "
                        "unique-row budget; raise uniq_bucket (or set 0 "
                        "to re-probe) to recover effective batch size",
                        uniq_bucket, 100 * epoch_stats.spill_fraction)
            if multi_process and not stopping and epoch + 1 < cfg.epoch_num:
                # Adaptive bucket: a probe-missed dense stretch spills
                # every epoch otherwise. The job-wide spill fraction is
                # allgathered (per-process stats see only their own
                # shard — a local decision would desynchronize shapes
                # and deadlock the collective program), and every
                # process applies the same doubling.
                from jax.experimental import multihost_utils
                from fast_tffm_tpu.parallel.liveness import (
                    guarded_collective)
                tot = guarded_collective(
                    multihost_utils.process_allgather,
                    np.asarray(
                        [epoch_stats.spilled_batches, epoch_stats.batches,
                         epoch_stats.max_uniq]),
                    label="train/spill_stats")
                tot = tot.reshape(-1, 3)
                # fmlint: disable=R001 -- tot is the HOST numpy result
                # of process_allgather; these ints never touch a device
                uniq_bucket = adapt_uniq_bucket(
                    cfg, uniq_bucket, int(tot[:, 0].sum()),
                    int(tot[:, 1].sum()), logger,
                    max_uniq=int(tot[:, 2].max()))
            if not stopping:
                # The epoch boundary IS a vocab barrier point: the
                # epoch's observations admit/evict here, so the next
                # epoch (and the validation sweep just below) runs
                # against the refreshed map + reset rows.
                _vocab_barrier(f"epoch {epoch}")
            if cfg.validation_files and not stopping:
                # fmlint: disable=R003 -- feeds the train/
                # validation_seconds counter (the train/validation span
                # is the timeline view)
                t_val = time.perf_counter()
                vmb = cfg.validation_max_batches or None
                with span("train/validation", epoch=epoch):
                    if multi_process:
                        # preempt rides the lockstep window allgather:
                        # a SIGTERM during a long validation sweep
                        # stops EVERY worker at the same window
                        # boundary (the signalled worker alone bailing
                        # would desync the collective program stream);
                        # the step loop below then drains the flag and
                        # all workers save together.
                        auc, n = evaluate_distributed(
                            cfg, table, cfg.validation_files, mesh,
                            shard_index, num_shards,
                            uniq_bucket=val_bucket, max_batches=vmb,
                            weight_files=cfg.validation_weight_files,
                            bad_lines=bad_tracker,
                            preempt=lambda: bool(preempted))
                    else:
                        auc, n = evaluate(
                            cfg, table, cfg.validation_files,
                            mesh=mesh, backend=lk, max_batches=vmb,
                            weight_files=cfg.validation_weight_files,
                            bad_lines=bad_tracker, vocab=vocab)
                last_val = (auc, n)
                if jax.process_index() == 0:
                    logger.info(
                        "epoch %d validation AUC %.6f over %d examples",
                        epoch, auc, n)
                if summaries is not None:
                    summaries.add("validation/auc", global_step, auc)
                if tel is not None:
                    # fmlint: disable=R003 -- closes the pause sample
                    tel.count("train/validation_seconds",
                              time.perf_counter() - t_val)
                    tel.set("validation/auc", auc)
                    # fmlint: disable=R001 -- auc is already a host
                    # python float from the streamed AUC merge
                    tel.add_scalar("validation/auc", global_step,
                                   float(auc))
            if summaries is not None:  # epoch barrier: bulk-fetch + write
                # fmlint: disable=R003 -- feeds the train/
                # summary_pause_seconds counter (always-on aggregate)
                t_sum = time.perf_counter()
                summaries.flush()
                if tel is not None:
                    # fmlint: disable=R003 -- closes the pause sample
                    tel.count("train/summary_pause_seconds",
                              time.perf_counter() - t_sum)
            if tel is not None:
                # Epoch barrier: the one point buffered device scalars
                # are bulk-fetched and the JSONL reaches disk for sure.
                tel.count("train/epochs")
                tel.barrier_flush(global_step)
            if not stopping:  # a preemption-cut epoch is NOT completed
                completed_epochs = epoch + 1
            if (grow_ctx is not None and not stopping
                    and completed_epochs < cfg.epoch_num):
                # The epoch boundary IS the grow barrier in epochs
                # mode: every worker is synchronized here (the same
                # point the vocab barrier uses), and the chief's
                # admission plan is broadcast so everyone raises
                # together or nobody does. The barrier state is saved
                # durably FIRST (force rewrites a same-step periodic
                # save with the completed epoch count) — it is exactly
                # what the newcomer's verified restore comes up on.
                # The last epoch never grows: the run is about to
                # finish, and a reform would only delay its exit.
                plan = grow_ctx.check_barrier()
                if plan is not None:
                    state = (lk.state() if offload
                             else ckpt_state(cfg, table, acc))
                    ckpt.save(global_step, *state,
                              vocabulary_size=cfg.vocabulary_size,
                              force=True, wait=True,
                              epoch=completed_epochs,
                              vocab_state=(vocab.state_payload()
                                           if vocab is not None
                                           else None))
                    last_periodic_save = (global_step,
                                          completed_epochs)
                    raise ClusterGrowth(plan)
        flush_log()
        loss_val = float(loss) if loss is not None else loss_val
        # The final save IS a barrier point (vocab/table.py's contract):
        # nothing is in flight here — the stream is drained or the
        # epoch iterators exhausted — so the durable (table, slot map)
        # pair admits the last interval's crossers and evicts/resets
        # its cold rows before the bytes land (the exit publish below
        # repoints at exactly this state). MUST run before state() is
        # captured: the row resets donate (and for the device path
        # reassign) the table/acc buffers.
        _vocab_barrier(f"final save step {global_step}")
        state = lk.state() if offload else ckpt_state(cfg, table, acc)
        # Final/preemption save: barrier until durably written — the
        # process may exit right after.
        # If this step's existing checkpoint carries a stale epoch
        # count — from THIS run's last periodic save, or from the
        # RESTORED checkpoint when a resumed run advanced the schedule
        # without a single global step (every shard's input empty —
        # note a multi-process job with ANY data still advances
        # global_step via lockstep fillers, so that case needs the
        # whole job dry) — tell save() to correct it (an atomic epoch
        # sidecar written by process 0; restore overlays it). Both
        # signals are deterministic (lockstep-consistent state, not
        # disk reads), so every process of a multi-host job agrees the
        # correction exists — restore's process-0-read + broadcast does
        # the rest.
        stale = ((last_periodic_save[0] == global_step
                  and last_periodic_save[1] != completed_epochs)
                 or (restored is not None
                     and global_step == restored_step
                     and completed_epochs != restored_epoch))
        ckpt.save(global_step, *state,
                  vocabulary_size=cfg.vocabulary_size, force=True,
                  wait=True, epoch=completed_epochs,
                  rewrite_stale_metadata=stale,
                  stream_state=_stream_state_for_save(),
                  vocab_state=(vocab.state_payload()
                               if vocab is not None else None))
        if stream_mode and getattr(cfg, "publish_interval_seconds",
                                   0.0) > 0:
            # The exit publish: a clean STOP drain (or a preemption's
            # durable save) is the freshest verified state a scorer
            # can hot-reload; the save above already settled the
            # manifest (wait=True). Gated like every other publish —
            # a run whose tail regressed quality must exit with the
            # pointer still on the last passing step (the final save
            # itself always lands: resume durability is not gated).
            # A PREEMPTED exit skips the quality sweep: the grace
            # window between SIGTERM and the orchestrator's SIGKILL
            # has no budget for a validation pass, and a mid-sweep
            # kill would lose the publish entirely — so a gate-less
            # run publishes immediately (the historical behavior) and
            # a gated run leaves the pointer on the last step the gate
            # actually passed rather than publishing unevaluated state.
            if stopping and gate is not None:
                logger.info(
                    "preempted with a publish gate configured: exit "
                    "publish skipped (no quality sweep inside the "
                    "grace window); the pointer stays on the last "
                    "passing step")
            else:
                decision = (None if stopping
                            else _publish_decision())
                if decision is not None:
                    # The exit sweep IS this table's final validation:
                    # _chief_finalize (multi-process) must not re-run
                    # it.
                    last_val = (decision["auc"], decision["examples"])
                if decision is None or not decision.get("held"):
                    if ckpt.publish_step(global_step) is not None:
                        # Persist the exit publish's baseline too —
                        # it is exactly what the NEXT run's gate must
                        # re-arm from.
                        _gate_published(decision)
                        if tel is not None:
                            tel.set("stream/last_publish_age_seconds",
                                    0.0)
        if (stream_mode and not multi_process and cfg.validation_files
                and not quality_on):
            # Stream mode has no per-epoch sweeps; a configured
            # validation corpus gets one final scored pass here
            # (multi-process streams validate in _chief_finalize below;
            # publishing streams already validated through the exit
            # publish's quality sweep just above) — silently
            # accepting-and-ignoring the knob would be a config trap.
            auc, n = evaluate(
                cfg, table, cfg.validation_files, mesh=mesh,
                backend=lk, max_batches=cfg.validation_max_batches
                or None, weight_files=cfg.validation_weight_files,
                bad_lines=bad_tracker, vocab=vocab)
            logger.info("final validation AUC %.6f over %d examples",
                        auc, n)
            if tel is not None:
                tel.set("validation/auc", auc)
                # fmlint: disable=R001 -- auc is already a host float
                # from the streamed AUC merge
                tel.add_scalar("validation/auc", global_step,
                               float(auc))
        if multi_process:
            _chief_finalize(cfg, table, logger, mesh, shard_index,
                            num_shards, last_val, val_bucket,
                            bad_tracker)
        else:
            # Same size gate on EVERY dense-export path: a single-host
            # mesh whose aggregate row-sharded table exceeds host RAM
            # must not OOM assembling the .npz after a successful run.
            nbytes = table_bytes(cfg)
            if nbytes > EXPORT_NPZ_MAX_BYTES:
                logger.info(
                    "skipping dense .npz export: table is "
                    "%.1f GB > %.1f GB threshold; use the checkpoint at "
                    "%s.ckpt", nbytes / 2**30,
                    EXPORT_NPZ_MAX_BYTES / 2**30, cfg.model_file)
            else:
                export_npz(lk.table if offload else table,
                           cfg.model_file + ".npz",
                           vocabulary_size=cfg.vocabulary_size)
    except BaseException as e:
        # Crash forensics: the stream's last substantive event carries
        # the traceback and the recent-event ring, with the step
        # attached. A WorkerLostError is NOT a crash yet — the elastic
        # driver may recover it; the driver records it if it decides
        # to re-raise instead.
        from fast_tffm_tpu.parallel.liveness import WorkerLostError
        worker_lost = isinstance(e, WorkerLostError)
        if not worker_lost and not isinstance(e, ClusterGrowth):
            # ClusterGrowth is a planned, durably-saved barrier exit —
            # the driver reforms and re-enters; branding it a crash
            # would flip every healed run's verdict to CRASHED.
            _record_crash(tel, logger, e, global_step)
        raise
    finally:
        # The session's resident allocations leave the ledger here —
        # crash or clean exit — so an elastic-recovered session
        # re-registers fresh sizes instead of double-counting, and the
        # peak watermark (deliberately NOT reset) keeps the high-water
        # answer across recoveries.
        for _owner in ("table", "adagrad_acc", "offload_table",
                       "offload_acc", "wire_buffers",
                       "prefetch_batches", "lockstep_window"):
            LEDGER.release(_owner)
        try:
            if worker_lost:
                # HOST-ONLY teardown: a peer is dead, so any device
                # fetch (buffered loss scalars, TB summaries, the
                # deferred log buffer — all outputs of collective
                # programs that will never complete) and any orbax
                # multi-host commit barrier (ckpt.close) can block
                # forever — the exact hang the deadline guard just
                # escaped. Drop the device-side buffers (counted, not
                # silent), flush host events, and let the elastic
                # driver rebuild the checkpoint manager; the verified
                # restore walk-back owns anything torn.
                if tel is not None:
                    try:
                        dropped = tel.sink.discard_scalars()
                        if dropped:
                            tel.count("cluster/scalars_dropped", dropped)
                        tel.sink.flush()
                    except Exception:
                        logger.exception("host-only metrics flush "
                                         "failed")
                logger.warning(
                    "worker lost: skipped checkpoint close and "
                    "device-scalar drains (device fetches could hang "
                    "on the dead peer's collectives)")
            else:
                # Checkpoint lifecycle on ALL normal exit paths: an
                # exception (or preemption) between the last periodic
                # save and the normal close must not leave an async
                # save in flight — the process would exit mid-write
                # and tear the newest step. close() waits for the
                # in-flight write, settles the owed integrity
                # manifest, and releases the manager; isolated so a
                # failed close can't starve the sink drains below.
                if ckpt is not None:
                    try:
                        ckpt.close()
                    except Exception:
                        logger.exception("checkpoint close failed")
                # Sink lifecycle on error paths: a crash mid-epoch
                # must not drop everything buffered since the last
                # flush — the log buffer and the TensorBoard scalars
                # drain here, each isolated so one broken writer can't
                # starve the others. (The metrics sink and bad-line
                # tracker are DRIVER-scoped: they survive elastic
                # recoveries and close in train().)
                try:
                    flush_log()
                except Exception:
                    logger.exception("deferred loss-log flush failed")
                if summaries is not None:
                    # Buffered scalars must reach the event file even
                    # when the loop raised or a preemption cut the
                    # final epoch.
                    try:
                        summaries.close()
                    except Exception:
                        logger.exception("summary writer close failed")
                if tel is not None:
                    try:
                        # Barrier, not close: buffered device scalars
                        # and the final counter snapshot reach disk
                        # with this session's step attached, and the
                        # stream stays open for a recovered session to
                        # continue.
                        tel.barrier_flush(global_step)
                    except Exception:
                        logger.exception("metrics barrier flush failed")
            if profiling:
                # Window ran past the end of training — or the loop
                # raised with the window open; either way the trace must
                # be closed here or the next start_trace in this process
                # fails with "trace already in progress".
                jax.profiler.stop_trace()
                profiling = False
        finally:
            # Must run even if stop_trace raises (unwritable profile_dir):
            # leaving these handlers installed would swallow SIGTERM/
            # SIGINT into a dead flag list in the surviving process.
            for sig, h in prev_handlers.items():
                signal.signal(sig, h)
    logger.info("training done: %d steps, final loss %.6f, %.0f examples/sec",
                global_step, loss_val, timer.total_examples_per_sec)
    if offload:
        # The logical table as host numpy (the offload analogue of the
        # device table return; dead ckpt-alignment tail sliced off).
        # The pinned backend's table is a jax array in accelerator-host
        # memory: fetch it (callers of train() expect host bytes; at
        # true config-#5 scale callers use the checkpoint instead).
        tbl = (lk.table if isinstance(lk.table, np.ndarray)
               else np.asarray(jax.device_get(lk.table)))
        return tbl[:cfg.num_rows]
    return table


# Above this, the dense .npz convenience export is skipped (the real
# model lives in the sharded checkpoint): a 10^9-row table is ~36 GB
# dense — materializing it on one host is exactly what the sharded
# design exists to avoid.
EXPORT_NPZ_MAX_BYTES = 2 << 30


# Shrink threshold: halve the bucket only when the epoch's DENSEST
# batch used under this fraction of it — the halved bucket then still
# holds that batch with >= 1/(2*0.35) ~ 1.4x headroom, so the shrink
# cannot itself cause next-epoch spills on this data.
SHRINK_FILL_FRACTION = 0.35


def adapt_uniq_bucket(cfg: FmConfig, uniq_bucket: int, spilled: int,
                      batches: int, logger, max_uniq: int = 0) -> int:
    """Next epoch's fixed unique-row bucket, given THIS epoch's job-wide
    stats: double (up to the worst-case ladder top) while the spill
    fraction stays above SPILL_WARN_FRACTION; halve (never below 64 or
    the single-example bound) after a spill-free epoch whose densest
    batch (``max_uniq``, job-wide max) filled under SHRINK_FILL_FRACTION
    of the bucket — an overshot startup probe or a dense early file
    otherwise inflates every later step's gather/scatter width for the
    rest of the job (round-4 review). Deterministic in its inputs —
    callers must feed every process the same totals (train() allgathers
    them) so all agree on the new batch shapes without negotiation. An
    explicit ``uniq_bucket`` config is never overridden.
    """
    if cfg.uniq_bucket or not batches:
        return uniq_bucket
    if spilled / batches > SPILL_WARN_FRACTION:
        top = uniq_bucket_top(cfg)
        if uniq_bucket >= top:
            return uniq_bucket
        new_bucket = min(uniq_bucket * 2, top)
        logger.info(
            "raising uniq_bucket %d -> %d for the next epoch (%.0f%% of "
            "batches spilled on the unique-row budget this epoch)",
            uniq_bucket, new_bucket, 100 * spilled / batches)
        return new_bucket
    half = uniq_bucket // 2
    if (spilled == 0 and max_uniq
            and max_uniq <= uniq_bucket * SHRINK_FILL_FRACTION
            and half >= 64
            # config invariant: the bucket must exceed the per-example
            # feature cap or one dense example could overflow it outright
            and half > cfg.max_features_per_example):
        logger.info(
            "lowering uniq_bucket %d -> %d for the next epoch (densest "
            "batch used %d unique rows, %.0f%% fill — recovering "
            "gather/scatter width from an oversized probe or an earlier "
            "raise)", uniq_bucket, half, max_uniq,
            100 * max_uniq / uniq_bucket)
        return half
    return uniq_bucket


def _chief_finalize(cfg: FmConfig, table: jax.Array, logger, mesh,
                    shard_index: int, num_shards: int,
                    last_val=None, val_bucket: int = 0,
                    bad_tracker=None) -> None:
    """Multi-process epilogue: final validation AUC via the sharded
    score fn (table stays row-sharded; only binned histograms cross
    hosts), then a size-gated dense export assembled chunk-by-chunk so
    no host ever holds more than the chief's final copy.

    ``last_val`` is the last per-epoch (auc, n): when the final epoch
    already validated this exact table, re-sweeping validation_files
    (every batch a collective) would just recompute it."""
    from jax.experimental import multihost_utils
    from fast_tffm_tpu.parallel.liveness import guarded_collective
    if cfg.validation_files:
        if last_val is None:  # e.g. preemption cut the epoch short
            # Same cap as the per-epoch sweeps: an uncapped fallback
            # here would run a full lockstep validation inside a
            # preemption grace window.
            last_val = evaluate_distributed(
                cfg, table, cfg.validation_files, mesh, shard_index,
                num_shards, uniq_bucket=val_bucket,
                max_batches=cfg.validation_max_batches or None,
                weight_files=cfg.validation_weight_files,
                bad_lines=bad_tracker)
        if jax.process_index() == 0:
            logger.info("final validation AUC %.6f over %d examples",
                        *last_val)
    nbytes = table_bytes(cfg)
    if nbytes > EXPORT_NPZ_MAX_BYTES:
        if jax.process_index() == 0:
            logger.info(
                "skipping dense .npz export: table is %.1f GB > %.1f GB "
                "threshold; use the sharded checkpoint at %s.ckpt",
                nbytes / 2**30, EXPORT_NPZ_MAX_BYTES / 2**30,
                cfg.model_file)
    else:
        # Chunked allgather: every process participates (collective),
        # non-chief hosts drop each chunk immediately, so peak extra
        # host memory is one chunk — not the whole table — everywhere
        # but the chief, which writes chunks straight into the one
        # preallocated dense buffer the .npz needs anyway.
        chunk = max(1, (64 << 20) // (cfg.row_dim * 4))
        chief = jax.process_index() == 0
        out = (np.empty((cfg.num_rows, cfg.row_dim), np.float32)
               if chief else None)
        for a in range(0, cfg.num_rows, chunk):
            b = min(a + chunk, cfg.num_rows)
            piece = guarded_collective(
                multihost_utils.process_allgather, table[a:b],
                tiled=True, label="finalize/export_chunk")
            if chief:
                out[a:b] = np.asarray(piece)
        if chief:
            export_npz(out, cfg.model_file + ".npz",
                       vocabulary_size=cfg.vocabulary_size)
    guarded_collective(multihost_utils.sync_global_devices,
                       "fast_tffm_tpu_finalize", label="finalize/sync")


def ckpt_state(cfg: FmConfig, table: jax.Array, acc: jax.Array):
    """Checkpoint contract: always store [ckpt_rows, D] — the fixed
    4096-aligned row layout (FmConfig.ckpt_rows) every topology shares,
    so a checkpoint saved by any mesh restores row-sharded on any other
    without assembling the table on one host. Mesh tables are already
    this shape (orbax saves them sharded — each host writes only its
    rows); single-device tables get the dead pad tail appended."""
    n_pad = cfg.ckpt_rows - int(table.shape[0])
    if n_pad == 0:
        return table, acc
    import jax.numpy as jnp
    pad_t = jnp.zeros((n_pad, cfg.row_dim), jnp.float32)
    pad_a = jnp.full((n_pad, cfg.row_dim), cfg.adagrad_init, jnp.float32)
    return (jnp.concatenate([table, pad_t], axis=0),
            jnp.concatenate([acc, pad_a], axis=0))


def checkpoint_template(cfg: FmConfig, mesh=None, host: bool = False):
    """Abstract pytree matching CheckpointState.save's layout — orbax
    needs it to restore from a process that didn't do the saving.

    The explicit sharding makes restore topology-portable: orbax places
    the arrays per THIS run's layout instead of repopulating whatever
    sharding the saving topology recorded (which, for a multi-host save
    restored elsewhere, would yield non-addressable arrays).

    ``host`` leaves the leaves sharding-free, which makes orbax restore
    plain np.ndarrays into host RAM — the offload-backend path, where
    the table must never land on a device."""
    shape = (cfg.ckpt_rows, cfg.row_dim)
    if host:
        return {"table": jax.ShapeDtypeStruct(shape, np.float32),
                "acc": jax.ShapeDtypeStruct(shape, np.float32),
                "step": 0, "epoch": 0, "vocab": 0}
    if mesh is not None:
        from jax.sharding import NamedSharding
        from fast_tffm_tpu.parallel.sharded import ROW_SPEC
        sh = NamedSharding(mesh, ROW_SPEC)
    else:
        sh = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    return {"table": jax.ShapeDtypeStruct(shape, np.float32, sharding=sh),
            "acc": jax.ShapeDtypeStruct(shape, np.float32, sharding=sh),
            "step": 0, "epoch": 0, "vocab": 0}


def resume_start_epoch(stored_epoch: int, epoch_num: int) -> int:
    """Where a restarted run's epoch loop begins.

    An INTERRUPTED schedule (0 < stored < epoch_num) resumes at the
    first incomplete epoch — restarting from zero would revisit the
    same data under the same per-epoch seeds and, under preemptions
    recurring faster than a full schedule, never terminate. A COMPLETED
    checkpoint (stored >= epoch_num, or a smaller epoch_num configured
    since) keeps the reference's semantics: invoking train again runs a
    fresh epoch_num-epoch schedule on top of the restored weights (the
    reference's TF1 queue epoch counters were process-local and never
    checkpointed, so it behaved exactly this way)."""
    return stored_epoch if 0 < stored_epoch < epoch_num else 0


def check_restored_vocab(cfg: FmConfig, restored) -> None:
    """The 4096-aligned storage shape can't distinguish vocabularies in
    the same bucket, so the stored vocab is verified explicitly — a
    mismatch would silently turn a trained row into the pad row."""
    v = int(restored["vocab"])
    if v != cfg.vocabulary_size:
        raise ValueError(
            f"checkpoint was written with vocabulary_size={v}, but this "
            f"config has vocabulary_size={cfg.vocabulary_size}; restoring "
            "would misalign the pad row and feature ids. Retrain, or fix "
            "the config.")
